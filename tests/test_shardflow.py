"""saturn-shardflow: sharding-propagation interpreter, SAT-X passes, and
the cold-start solver prior.

Three layers, mirroring the subsystem:

* **Interpreter rules** — hand-built jaxprs with known GSPMD consequences
  (contraction sharded both sides -> all-reduce, ZeRO-3 parameter gather,
  elementwise spec conflict -> reshard, scan trip-count folding,
  shard_map manual-mode suppression) checked byte-for-byte against the
  wire-cost model.
* **Passes** — SAT-X001..X005 each driven to fire and to stay quiet, plus
  the sanction marker's downgrade-never-silence contract.
* **Integration** — the cold-start admission path: a never-profiled task
  is ADMITted purely on static priors (zero trials, journaled
  ``static_prior=True``), realized feedback supersedes the prior, and
  SAT-X005 audits the superseded estimate.

The end-to-end static-vs-compiled-HLO agreement check lives in
``test_shardflow_differential.py``.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from saturn_tpu.analysis.diagnostics import SCHEMA_VERSION, AnalysisReport, make
from saturn_tpu.analysis.shardflow import PASS_VERSION
from saturn_tpu.analysis.shardflow import passes as sf_passes
from saturn_tpu.analysis.shardflow import prior as sf_prior
from saturn_tpu.analysis.shardflow.interp import (
    CollectiveRecord,
    CommLedger,
    Interpreter,
    interpret,
)

pytestmark = pytest.mark.analysis

F32 = jnp.float32


def sds(*shape):
    return jax.ShapeDtypeStruct(shape, F32)


def run_interp(fn, avals, specs, mesh_axes, axis_env=None,
               replicated_threshold=1 << 26):
    """Trace ``fn`` to a jaxpr and run the interpreter with explicit
    input specs (tuple-of-tuples form: one tuple of axis names per dim)."""
    closed = jax.make_jaxpr(fn, axis_env=list(axis_env or []))(*avals)
    it = Interpreter(mesh_axes, replicated_threshold=replicated_threshold)
    it.run(closed, specs)
    return it.ledger


class TestInterpreterRules:
    def test_contraction_sharded_both_sides_all_reduces_output(self):
        # A[4,8] x B[8,4] contracting on a 'data'-sharded dim: partial sums
        # on every shard -> all-reduce of the 4x4 output.
        def f(a, b):
            return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))

        led = run_interp(f, [sds(4, 8), sds(8, 4)],
                         [((), ("data",)), (("data",), ())], {"data": 4})
        by = led.by_op()
        assert set(by) == {"all_reduce"}
        assert by["all_reduce"]["bytes"] == 4 * 4 * 4
        # ring cost: 2(n-1)/n of the payload
        assert by["all_reduce"]["wire_bytes"] == pytest.approx(
            2.0 * 3 / 4 * 64)
        assert led.flops == pytest.approx(2.0 * 16 * 8)

    def test_one_sided_contraction_gathers_that_operand(self):
        def f(a, b):
            return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))

        led = run_interp(f, [sds(4, 8), sds(8, 4)],
                         [((), ("data",)), ((), ())], {"data": 4})
        by = led.by_op()
        assert set(by) == {"all_gather"}
        assert by["all_gather"]["bytes"] == 4 * 8 * 4  # the lhs, whole

    def test_zero3_parameter_gather(self):
        # batch sharded on 'data' meets a weight whose free dim is also
        # 'data'-sharded: GSPMD all-gathers the parameter (the ZeRO-3 /
        # fsdp pattern).
        def f(x, w):
            return x @ w

        led = run_interp(f, [sds(4, 8), sds(8, 16)],
                         [(("data",), ()), ((), ("data",))], {"data": 4})
        by = led.by_op()
        assert set(by) == {"all_gather"}
        assert by["all_gather"]["bytes"] == 8 * 16 * 4  # the weight, whole

    def test_compatible_shardings_move_no_bytes(self):
        def f(x, w):
            return x @ w

        led = run_interp(f, [sds(4, 8), sds(8, 16)],
                         [(("data",), ()), ((), ("model",))],
                         {"data": 4, "model": 2})
        assert led.records == []
        assert led.flops > 0

    def test_elementwise_conflict_records_reshard(self):
        def f(a, b):
            return a + b

        led = run_interp(f, [sds(8, 8), sds(8, 8)],
                         [(("data",), ()), (("model",), ())],
                         {"data": 2, "model": 2})
        assert led.resharded, "conflicting shardings must record a reshard"
        assert led.resharded[0].op == "reshard"
        assert set(led.resharded[0].axes) == {"data", "model"}

    def test_reduce_over_sharded_dim_all_reduces(self):
        def f(a):
            return a.sum(axis=0)

        led = run_interp(f, [sds(8, 4)], [(("data",), ())], {"data": 4})
        by = led.by_op()
        assert set(by) == {"all_reduce"}
        assert by["all_reduce"]["bytes"] == 4 * 4  # the (4,) output

    def test_explicit_psum_is_counted_and_flagged_explicit(self):
        def f(x):
            return jax.lax.psum(x, "data")

        led = run_interp(f, [sds(8)], [((),)], {"data": 4},
                         axis_env=[("data", 4)])
        assert len(led.records) == 1
        rec = led.records[0]
        assert rec.op == "all_reduce" and rec.explicit
        assert rec.bytes == 8 * 4

    def test_scan_folds_trip_count_and_marks_depth(self):
        def f(x):
            def body(c, _):
                return jax.lax.psum(c, "data"), None

            c, _ = jax.lax.scan(body, x, None, length=5)
            return c

        led = run_interp(f, [sds(4)], [((),)], {"data": 4},
                         axis_env=[("data", 4)])
        assert len(led.records) == 1
        rec = led.records[0]
        assert rec.count == 5 and rec.scan_depth == 1

    def test_one_wide_axis_moves_no_bytes(self):
        def f(x):
            return jax.lax.psum(x, "data")

        led = run_interp(f, [sds(8)], [((),)], {"data": 1},
                         axis_env=[("data", 1)])
        assert led.records == []

    def test_large_replicated_intermediate_is_flagged(self):
        def f(a):
            return jnp.broadcast_to(a.sum(), (64,))

        led = run_interp(f, [sds(8)], [((),)], {"data": 4},
                         replicated_threshold=128)
        assert led.replicated_intermediates
        assert max(b for b, _ in led.replicated_intermediates) >= 64 * 4
        # default 64 MiB threshold stays quiet on the same program
        quiet = run_interp(f, [sds(8)], [((),)], {"data": 4})
        assert quiet.replicated_intermediates == []


class TestShardMapMode:
    """Inside shard_map bodies sharding is manual: implicit GSPMD rules
    must not fire, only the body's explicit collectives count, and flops
    are rescaled from per-shard avals to the global workload."""

    def _mesh(self):
        return jax.sharding.Mesh(
            np.array(jax.devices()[:4]).reshape(4), ("data",))

    def test_only_explicit_collectives_counted(self, devices8):
        from saturn_tpu.ops.shmap_compat import shard_map

        mesh = self._mesh()

        def f(x):
            def body(x):
                # jnp.sum over the locally-sharded dim would trip the
                # implicit reduce rule if manual mode weren't respected
                return jax.lax.psum(jnp.sum(x * 2.0), "data")

            return shard_map(body, mesh=mesh, in_specs=(P("data", None),),
                             out_specs=P(), check_vma=False)(x)

        closed = jax.make_jaxpr(f)(sds(8, 8))
        it = Interpreter({"data": 4})
        it.run(closed, [(("data",), ())])
        by = it.ledger.by_op()
        assert set(by) == {"all_reduce"}
        assert by["all_reduce"]["bytes"] == 4  # the scalar psum
        assert all(r.explicit for r in it.ledger.records)

    def test_flops_rescaled_to_global(self, devices8):
        from saturn_tpu.ops.shmap_compat import shard_map

        mesh = self._mesh()

        def f(x):
            def body(x):
                y = x @ jnp.ones((8, 8), F32)  # per-shard (2,8)@(8,8)
                return jax.lax.psum(jnp.sum(y), "data")

            return shard_map(body, mesh=mesh, in_specs=(P("data", None),),
                             out_specs=P(), check_vma=False)(x)

        closed = jax.make_jaxpr(f)(sds(8, 8))
        it = Interpreter({"data": 4})
        it.run(closed, [(("data",), ())])
        # per-shard 2*16*8 flops x 4 shards == the global 2*64*8
        assert it.ledger.flops == pytest.approx(2.0 * 8 * 8 * 8)


class TestSourcePass:
    """SAT-X002 and the sanction marker contract."""

    BAD = (
        "from jax.experimental import multihost_utils\n"
        "\n"
        "def save(leaf):\n"
        "    return multihost_utils.process_allgather(leaf, tiled=True)\n"
    )
    SANCTIONED = (
        "from jax.experimental import multihost_utils\n"
        "\n"
        "def save(leaf):\n"
        "    # sanctioned-shardflow: unit test fixture\n"
        "    return multihost_utils.process_allgather(leaf, tiled=True)\n"
    )
    DEVICE_PUT = (
        "import jax\n"
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "\n"
        "def gather(leaf, mesh):\n"
        "    return jax.device_put(\n"
        "        leaf, NamedSharding(mesh, PartitionSpec()))\n"
    )

    def _scan(self, tmp_path, src, name="mod.py"):
        p = tmp_path / name
        p.write_text(src)
        report = AnalysisReport(subject="test-sources")
        sf_passes.scan_sources([str(p)], report)
        return report

    def test_unsanctioned_allgather_is_an_error(self, tmp_path):
        report = self._scan(tmp_path, self.BAD)
        assert not report.ok
        (d,) = report.errors
        assert d.code == "SAT-X002"
        assert d.location and d.location.endswith(":4")

    def test_replicated_device_put_is_an_error(self, tmp_path):
        report = self._scan(tmp_path, self.DEVICE_PUT)
        assert [d.code for d in report.errors] == ["SAT-X002"]

    def test_sanction_downgrades_but_never_silences(self, tmp_path):
        report = self._scan(tmp_path, self.SANCTIONED)
        assert report.ok, "sanctioned finding must not gate"
        infos = [d for d in report.diagnostics if d.severity == "info"]
        assert [d.code for d in infos] == ["SAT-X002"]
        assert "sanctioned" in infos[0].message

    def test_unparseable_source_is_sat_x000(self, tmp_path):
        report = self._scan(tmp_path, "def broken(:\n")
        assert [d.code for d in report.errors] == ["SAT-X000"]

    def test_intree_sources_are_clean(self):
        # the lint gate's exact invocation: zero SAT-X002 in the
        # technique/kernel packages AND the checkpoint module — the sharded
        # manifest format (round 19) removed the last gather funnels, so no
        # sanctioned infos remain either
        import saturn_tpu

        repo = __import__("os").path.dirname(
            __import__("os").path.dirname(saturn_tpu.__file__))
        report = AnalysisReport(subject="intree")
        sf_passes.scan_sources(sf_passes.default_source_paths(repo), report)
        assert report.ok, [d.to_json() for d in report.errors]
        assert [d.code for d in report.diagnostics
                if d.severity == "info"] == []


def _traced(step, state_sds, state_spec, batch_sds, batch_spec, mesh_axes,
            axis_env=None):
    return {
        "jaxpr": jax.make_jaxpr(step, axis_env=list(axis_env or []))(
            state_sds, batch_sds),
        "state_shapes": state_sds,
        "state_specs": state_spec,
        "batch_spec": batch_spec,
        "batch_sds": batch_sds,
        "mesh_axes": dict(mesh_axes),
        "technique": "fake",
        "size": 1,
        "config": {},
    }


class TestTracePasses:
    def test_sat_x001_implicit_reshard(self):
        def step(state, batch):
            return state + batch

        traced = _traced(step, sds(8, 8), P("data"), sds(8, 8), P("model"),
                         {"data": 2, "model": 2})
        report, ledger = sf_passes.analyze_traced(traced)
        assert not report.ok
        assert "SAT-X001" in report.codes()
        assert ledger.resharded

    def test_sat_x003_oversized_replicated_intermediate(self):
        def step(state, batch):
            return state + jnp.broadcast_to(jnp.sum(batch), (64,))

        traced = _traced(step, sds(64), P(), sds(8, 8), P("data"),
                         {"data": 4})
        report, _ = sf_passes.analyze_traced(traced,
                                             replicated_threshold=128)
        assert report.ok  # warning-severity: flags, never gates
        assert "SAT-X003" in report.codes()

    def test_sat_x004_cross_slice_collective_in_scan(self):
        def step(state, batch):
            def body(c, _):
                return jax.lax.psum(c, "data"), None

            c, _ = jax.lax.scan(body, state, None, length=3)
            return c + jnp.sum(batch)

        traced = _traced(step, sds(8), P("data"), sds(8, 8), P(),
                         {"data": 8}, axis_env=[("data", 8)])
        # 8 devices over 4-chip slices: the leading axis crosses DCN
        report, _ = sf_passes.analyze_traced(traced, slice_size=4)
        assert "SAT-X004" in [d.code for d in report.errors]
        # same program on a single slice is fine
        quiet, _ = sf_passes.analyze_traced(traced, slice_size=8)
        assert "SAT-X004" not in quiet.codes()

    def test_crossing_axes(self):
        assert sf_passes.crossing_axes({"data": 4, "model": 2}, None) \
            == frozenset()
        assert sf_passes.crossing_axes({"data": 4, "model": 2}, 8) \
            == frozenset()
        assert sf_passes.crossing_axes({"data": 4, "model": 2}, 4) \
            == frozenset({"data"})


class TestTraceStepIntegration:
    def test_dp_trace_yields_gradient_all_reduce(self, tiny_task, devices8):
        from saturn_tpu import library as lib

        if not lib.registered_names():
            lib.register_default_library()
        cls = lib.retrieve("dp")
        tech = cls() if isinstance(cls, type) else cls
        config = tech.candidate_configs(tiny_task, 4)[0]
        traced = tech.trace_step(tiny_task, devices8[:4], config)
        for key in ("jaxpr", "state_shapes", "state_specs", "batch_spec",
                    "batch_sds", "mesh_axes", "technique", "size"):
            assert key in traced
        assert traced["mesh_axes"] == {"data": 4}
        ledger = interpret(traced)
        by = ledger.by_op()
        assert by.get("all_reduce", {}).get("bytes", 0) > 0
        assert ledger.flops > 0


class TestPrior:
    def _ledger(self, nbytes=1 << 20):
        led = CommLedger()
        led.add(CollectiveRecord(
            op="all_reduce", axes=("data",), bytes=nbytes,
            wire_bytes=1.5 * nbytes, count=1, primitive="psum",
            provenance="x:1", explicit=True))
        led.flops = 1e9
        return led

    def test_estimate_prices_crossing_axes_at_dcn(self):
        led = self._ledger()
        t_ici = sf_prior.estimate_step_seconds(led, 4)
        t_dcn = sf_prior.estimate_step_seconds(
            led, 4, crossing=frozenset({"data"}))
        assert t_dcn > t_ici * 5  # DCN is orders of magnitude slower

    def test_hardware_model_env_override(self, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_PRIOR_MFU", "0.9")
        assert sf_prior.hardware_model()["mfu"] == 0.9

    def test_audit_point_tolerance_boundary(self):
        assert sf_prior.audit_point(1.0, 1.3, "dp", 4) is None  # 23% ok
        d = sf_prior.audit_point(2.0, 1.0, "dp", 4)  # 100% off
        assert d is not None and d.code == "SAT-X005"
        assert d.severity == "warning"
        assert d.counterexample["relative_error"] == pytest.approx(1.0)

    def test_audit_skips_live_priors(self):
        class S:
            static_prior = True
            _static_prior_estimate = 1.0
            per_batch_time = 10.0
            executor = object()

        class T:
            strategies = {4: S()}

        assert sf_prior.audit_task(T()) == []

    def test_synthesize_then_feedback_then_audit(self, tiny_task, devices8):
        """The full prior lifecycle on a real task: synthesize (no trials,
        no compiles) -> live prior -> realized feedback supersedes it ->
        SAT-X005 flags the miscalibration."""
        from saturn_tpu.core.mesh import SliceTopology

        topo = SliceTopology(devices8)
        added = sf_prior.synthesize_strategies(
            tiny_task, topo, technique_names=["dp"])
        assert added == [1, 2, 4, 8]
        for g in added:
            s = tiny_task.strategies[g]
            assert s.static_prior
            assert s.per_batch_time > 0
            assert s.cache_key
            assert s._static_prior_estimate == pytest.approx(
                s.per_batch_time)
        # never overwrites existing points
        assert sf_prior.synthesize_strategies(
            tiny_task, topo, technique_names=["dp"]) == []
        # no audit while the prior is live
        assert sf_prior.audit_task(tiny_task) == []

        strat = tiny_task.strategies[4]
        tiny_task._pending_realized = (strat, strat.per_batch_time * 10)
        tiny_task.apply_realized_feedback()
        assert strat.static_prior is False
        diags = sf_prior.audit_task(tiny_task)
        assert [d.code for d in diags] == ["SAT-X005"]


class TestColdStartAdmission:
    """Acceptance: a never-profiled arrival is gated on the static prior
    alone — zero trials, journaled ``static_prior=True`` — and realized
    feedback later corrects the estimate under a SAT-X005 audit."""

    def test_admit_on_static_prior_then_audit(self, tiny_task, devices8,
                                              tmp_path):
        from saturn_tpu.core.mesh import SliceTopology
        from saturn_tpu.service.admission import ADMIT, AdmissionController
        from saturn_tpu.service.queue import JobRequest, SubmissionQueue
        from saturn_tpu.utils import metrics

        topo = SliceTopology(devices8)
        queue = SubmissionQueue()
        rec = queue.submit(JobRequest(task=tiny_task))
        ctrl = AdmissionController(topo, queue, technique_names=["dp"],
                                   static_priors=True)
        journal = []

        class Journal:
            def append(self, kind, **fields):
                journal.append((kind, fields))

        ctrl.journal = Journal()
        dec = ctrl.admit(rec, topo)

        assert dec.action == ADMIT
        assert dec.static_prior is True
        assert dec.trials_run == 0, "cold start must cost zero trials"
        assert dec.reason == "static prior"
        kinds = [k for k, _ in journal]
        assert kinds == ["job_admission"]
        assert journal[0][1]["static_prior"] is True
        assert all(s.static_prior
                   for s in tiny_task.feasible_strategies().values())

        # realized feedback supersedes the prior; the audit catches the
        # (deliberately huge) miscalibration as SAT-X005
        strat = tiny_task.strategies[max(tiny_task.feasible_strategies())]
        tiny_task._pending_realized = (strat, strat.per_batch_time * 10)
        tiny_task.apply_realized_feedback()
        assert strat.static_prior is False

        mpath = str(tmp_path / "metrics.jsonl")
        with metrics.scoped(mpath):
            ctrl._audit_priors(rec, tiny_task)
        evs = metrics.read_events(mpath, kind="shardflow_audit")
        assert evs and evs[0]["code"] == "SAT-X005"
        assert evs[0]["task"] == rec.name


class TestSolverJournal:
    def test_anytime_report_counts_static_prior_assignments(self, tmp_path):
        from saturn_tpu.core.mesh import SliceTopology
        from saturn_tpu.core.strategy import Strategy
        from saturn_tpu.solver import anytime
        from saturn_tpu.utils import metrics

        class FakeDev:
            pass

        class FakeTask:
            def __init__(self, name, runtimes, static):
                self.name = name
                self.strategies = {
                    g: Strategy(object(), g, {}, rt, 0.1,
                                static_prior=static)
                    for g, rt in runtimes.items()
                }

            def feasible_strategies(self):
                return self.strategies

        tp = SliceTopology([FakeDev() for _ in range(8)])
        tasks = [
            FakeTask("prior-a", {2: 8.0, 4: 5.0}, static=True),
            FakeTask("prior-b", {2: 6.0, 4: 4.0}, static=True),
            FakeTask("measured", {2: 7.0, 4: 4.5}, static=False),
        ]
        plan, report = anytime.anytime_solve(tasks, tp, deadline=0.5)
        assert len(plan.assignments) == 3
        assert report.n_static_prior == 2

        # the journaled solver_tier event carries the count (resolve path)
        mpath = str(tmp_path / "metrics.jsonl")
        with metrics.scoped(mpath):
            anytime.anytime_resolve(tasks, tp, None, 1.0, deadline=0.5,
                                    source="test")
        evs = metrics.read_events(mpath, kind="solver_tier")
        assert evs and evs[-1]["n_static_prior"] == 2


class TestReplanPropagation:
    def _task(self, static):
        from saturn_tpu.core.strategy import Strategy

        class T:
            name = "t"
            total_batches = 16
            chip_range = None

            def __init__(self):
                self.strategies = {
                    4: Strategy(object(), 4, {}, 40.0, 2.5,
                                static_prior=static),
                    8: Strategy(object(), 8, {}, 24.0, 1.5,
                                static_prior=static),
                }

            def feasible_strategies(self):
                return self.strategies

        return T()

    def test_all_static_anchors_propagate_the_flag(self):
        from saturn_tpu.resilience.replan import ElasticReplanner

        t = self._task(static=True)
        added = ElasticReplanner()._synthesize(t, 2)
        assert added
        assert all(t.strategies[g].static_prior for g in added)

    def test_measured_anchors_do_not(self):
        from saturn_tpu.resilience.replan import ElasticReplanner

        t = self._task(static=False)
        added = ElasticReplanner()._synthesize(t, 2)
        assert added
        assert not any(t.strategies[g].static_prior for g in added)


class TestCacheIdentity:
    def test_schema_version_bumped_for_shardflow(self):
        assert SCHEMA_VERSION >= 3
        assert PASS_VERSION >= 1

    def test_profile_fingerprint_tracks_pass_version(self, monkeypatch):
        import saturn_tpu.analysis.shardflow as sf_pkg
        from saturn_tpu.utils import profile_cache as pcache

        before = pcache.fingerprint("task", "dp", 4, "topo")
        monkeypatch.setattr(sf_pkg, "PASS_VERSION", 999 + PASS_VERSION)
        after = pcache.fingerprint("task", "dp", 4, "topo")
        assert before != after

    def test_aot_identity_tracks_pass_version(self, monkeypatch):
        import saturn_tpu.analysis.shardflow as sf_pkg
        from saturn_tpu.utils import aot_cache

        ident = aot_cache._runtime_identity()
        assert f"shardflow{PASS_VERSION}" in ident
        monkeypatch.setattr(sf_pkg, "PASS_VERSION", 999 + PASS_VERSION)
        assert aot_cache._runtime_identity() != ident


class TestCLI:
    def _fake_audit(self, report):
        def audit_intree(size=4, **kw):
            return report, {"dp": CommLedger()}

        return audit_intree

    def test_clean_audit_exits_zero(self, monkeypatch, capsys):
        from saturn_tpu.analysis import cli

        report = AnalysisReport(subject="shardflow-audit")
        monkeypatch.setattr(sf_passes, "audit_intree",
                            self._fake_audit(report))
        rc = cli.main(["--json", "shardflow"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert "ledgers" in payload

    def test_findings_exit_one(self, monkeypatch, capsys):
        from saturn_tpu.analysis import cli

        report = AnalysisReport(subject="shardflow-audit")
        report.add(make("SAT-X001", "error", "implicit reshard",
                        category="shardflow"))
        monkeypatch.setattr(sf_passes, "audit_intree",
                            self._fake_audit(report))
        assert cli.main(["shardflow"]) == 1
        capsys.readouterr()


class TestBenchGuard:
    def test_bench_shardflow_errors_clean_on_tree(self):
        import importlib.util
        import os

        import saturn_tpu

        repo = os.path.dirname(os.path.dirname(saturn_tpu.__file__))
        spec = importlib.util.spec_from_file_location(
            "bench_guard", os.path.join(repo, "benchmarks", "bench_guard.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.bench_shardflow_errors() == []
