"""Pretrained-weight ingestion (VERDICT r4 missing item 1).

The reference fine-tunes downloaded HF GPT-J weights
(``examples/wikitext103/models/GPTJ.py:502-526``); these tests exercise the
torch-state-dict → flax mapping offline with synthetically *written*
torch-format state dicts — no network anywhere. The GPT-2 path additionally
gets a true logits-parity check against an HF ``GPT2LMHeadModel`` built from
a config (transformers is in-image; random-initialized, not downloaded).
"""

import numpy as np
import pytest

from saturn_tpu.models.gpt2 import build_gpt2, config_for
from saturn_tpu.models import ingest

TINY = dict(d_model=64, n_layers=2, n_heads=4, vocab_size=256, seq_len=64)


def _gpt2_sd(cfg, rng, n_positions=None, vocab=None, prefix="transformer."):
    """Synthetic HF-GPT-2-naming state dict (Conv1D layout: (in, out))."""
    D, F = cfg.d_model, cfg.ff_dim
    V = vocab or cfg.vocab_size
    T = n_positions or cfg.seq_len
    sd = {
        f"{prefix}wte.weight": rng.normal(size=(V, D)) * 0.02,
        f"{prefix}wpe.weight": rng.normal(size=(T, D)) * 0.01,
        f"{prefix}ln_f.weight": rng.normal(size=(D,)) * 0.1 + 1,
        f"{prefix}ln_f.bias": rng.normal(size=(D,)) * 0.01,
    }
    for i in range(cfg.n_layers):
        h = f"{prefix}h.{i}."
        sd[h + "ln_1.weight"] = rng.normal(size=(D,)) * 0.1 + 1
        sd[h + "ln_1.bias"] = rng.normal(size=(D,)) * 0.01
        sd[h + "ln_2.weight"] = rng.normal(size=(D,)) * 0.1 + 1
        sd[h + "ln_2.bias"] = rng.normal(size=(D,)) * 0.01
        sd[h + "attn.c_attn.weight"] = rng.normal(size=(D, 3 * D)) * 0.02
        sd[h + "attn.c_attn.bias"] = rng.normal(size=(3 * D,)) * 0.01
        sd[h + "attn.c_proj.weight"] = rng.normal(size=(D, D)) * 0.02
        sd[h + "attn.c_proj.bias"] = rng.normal(size=(D,)) * 0.01
        sd[h + "mlp.c_fc.weight"] = rng.normal(size=(D, F)) * 0.02
        sd[h + "mlp.c_fc.bias"] = rng.normal(size=(F,)) * 0.01
        sd[h + "mlp.c_proj.weight"] = rng.normal(size=(F, D)) * 0.02
        sd[h + "mlp.c_proj.bias"] = rng.normal(size=(D,)) * 0.01
    return {k: v.astype(np.float32) for k, v in sd.items()}


def _gptj_sd(cfg, rng):
    """Synthetic HF-GPT-J-naming state dict (Linear layout: (out, in))."""
    D, F, V = cfg.d_model, cfg.ff_dim, cfg.vocab_size
    sd = {
        "transformer.wte.weight": rng.normal(size=(V, D)) * 0.02,
        "transformer.ln_f.weight": rng.normal(size=(D,)) * 0.1 + 1,
        "transformer.ln_f.bias": rng.normal(size=(D,)) * 0.01,
        "lm_head.weight": rng.normal(size=(V, D)) * 0.02,
        "lm_head.bias": rng.normal(size=(V,)) * 0.01,
    }
    for i in range(cfg.n_layers):
        h = f"transformer.h.{i}."
        sd[h + "ln_1.weight"] = rng.normal(size=(D,)) * 0.1 + 1
        sd[h + "ln_1.bias"] = rng.normal(size=(D,)) * 0.01
        for p in ("q_proj", "k_proj", "v_proj", "out_proj"):
            sd[h + f"attn.{p}.weight"] = rng.normal(size=(D, D)) * 0.02
        sd[h + "mlp.fc_in.weight"] = rng.normal(size=(F, D)) * 0.02
        sd[h + "mlp.fc_in.bias"] = rng.normal(size=(F,)) * 0.01
        sd[h + "mlp.fc_out.weight"] = rng.normal(size=(D, F)) * 0.02
        sd[h + "mlp.fc_out.bias"] = rng.normal(size=(D,)) * 0.01
    return {k: v.astype(np.float32) for k, v in sd.items()}


class TestGPT2Mapping:
    def test_values_land_in_place(self):
        cfg = config_for("test-tiny")
        sd = _gpt2_sd(cfg, np.random.default_rng(0))
        params, unused = ingest.gpt2_params_from_state_dict(dict(sd), cfg)
        assert unused == []
        # Conv1D layout: no transposes — exact array equality per layer slot
        np.testing.assert_array_equal(
            params["blocks"]["mlp_in"]["kernel"][1],
            sd["transformer.h.1.mlp.c_fc.weight"],
        )
        np.testing.assert_array_equal(
            params["blocks"]["qkv"]["kernel"][0],
            sd["transformer.h.0.attn.c_attn.weight"],
        )
        np.testing.assert_array_equal(
            params["ln_f"]["scale"], sd["transformer.ln_f.weight"]
        )
        np.testing.assert_array_equal(params["wte"],
                                      sd["transformer.wte.weight"])

    def test_vocab_pad_and_position_slice(self):
        cfg = config_for("test-tiny")
        sd = _gpt2_sd(cfg, np.random.default_rng(1), n_positions=128,
                      vocab=250)
        params, _ = ingest.gpt2_params_from_state_dict(dict(sd), cfg)
        assert params["wte"].shape == (256, 64)
        np.testing.assert_array_equal(params["wte"][250:], 0.0)
        # learned positions beyond seq_len are sliced away
        assert params["wpe"].shape == (64, 64)
        np.testing.assert_array_equal(
            params["wpe"], sd["transformer.wpe.weight"][:64]
        )

    def test_too_few_positions_raises(self):
        cfg = config_for("test-tiny")
        sd = _gpt2_sd(cfg, np.random.default_rng(2), n_positions=32)
        with pytest.raises(ValueError, match="positions"):
            ingest.gpt2_params_from_state_dict(dict(sd), cfg)

    def test_oversized_vocab_raises(self):
        cfg = config_for("test-tiny")
        sd = _gpt2_sd(cfg, np.random.default_rng(3), vocab=512)
        with pytest.raises(ValueError, match="vocab_size"):
            ingest.gpt2_params_from_state_dict(dict(sd), cfg)


class TestGPTJMapping:
    def test_transposes_and_qkv_fusion(self):
        cfg = config_for("gptj-test-tiny")
        sd = _gptj_sd(cfg, np.random.default_rng(0))
        params, unused = ingest.gptj_params_from_state_dict(dict(sd), cfg)
        D = cfg.d_model
        # Linear layout transposes; q|k|v concatenated on the out axis
        np.testing.assert_array_equal(
            params["blocks"]["qkv"]["kernel"][1, :, :D],
            sd["transformer.h.1.attn.q_proj.weight"].T,
        )
        np.testing.assert_array_equal(
            params["blocks"]["qkv"]["kernel"][1, :, 2 * D:],
            sd["transformer.h.1.attn.v_proj.weight"].T,
        )
        np.testing.assert_array_equal(
            params["blocks"]["mlp_out"]["kernel"][0],
            sd["transformer.h.0.mlp.fc_out.weight"].T,
        )
        # bias-free attention projections become zero biases
        np.testing.assert_array_equal(params["blocks"]["qkv"]["bias"], 0.0)
        np.testing.assert_array_equal(
            params["blocks"]["attn_out"]["bias"], 0.0
        )
        # untied lm_head is reported unused by default (tied-wte design)
        assert unused == []
        np.testing.assert_array_equal(params["wte"],
                                      sd["transformer.wte.weight"])

    def test_tie_from_lm_head(self):
        cfg = config_for("gptj-test-tiny")
        sd = _gptj_sd(cfg, np.random.default_rng(1))
        params, _ = ingest.gptj_params_from_state_dict(
            dict(sd), cfg, tie_from_lm_head=True
        )
        np.testing.assert_array_equal(params["wte"], sd["lm_head.weight"])


class TestDispatchAndValidation:
    def test_unknown_family_raises(self):
        cfg = config_for("test-tiny")
        with pytest.raises(ValueError, match="unrecognized"):
            ingest.params_from_state_dict({"encoder.layer.0.w": 1}, cfg)

    def test_wrong_preset_fails_loudly(self):
        # A GPT-2 dict mapped under a preset with different shapes must name
        # the mismatched paths, not surface as an XLA error later.
        cfg = config_for("test-tiny")
        sd = _gpt2_sd(cfg, np.random.default_rng(0))
        spec = build_gpt2("test-tiny", d_model=32)
        import jax

        params, _ = ingest.gpt2_params_from_state_dict(dict(sd), cfg)
        template = jax.eval_shape(
            lambda: spec.init_fn(jax.random.PRNGKey(0))
        )
        with pytest.raises(ValueError, match="wte"):
            ingest.validate_against(params, template)

    def test_build_gpt2_pretrained_wiring(self, tmp_path):
        """End to end through the factory + Task.get_model kwargs, via a
        real torch-format file (the reference's fine-tuning entry,
        ``GPTJ.py:502-526``)."""
        import torch

        cfg = config_for("test-tiny")
        sd = _gpt2_sd(cfg, np.random.default_rng(4))
        path = str(tmp_path / "weights.pt")
        torch.save({k: torch.from_numpy(v) for k, v in sd.items()}, path)

        import jax

        spec = build_gpt2("test-tiny", pretrained=path)
        params = spec.init_fn(jax.random.PRNGKey(0))
        np.testing.assert_allclose(
            np.asarray(params["wte"]), sd["transformer.wte.weight"],
            rtol=1e-6,
        )
        # forward runs on the ingested weights
        tokens = np.zeros((2, cfg.seq_len), dtype=np.int32)
        logits = spec.apply_fn(params, tokens)
        assert logits.shape == (2, cfg.seq_len, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

        # and through HParams.kwargs — the Task-level wiring
        from saturn_tpu import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.loss import pretraining_loss

        t = Task(
            get_model=lambda **kw: build_gpt2("test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=4, vocab_size=256,
                n_tokens=64 * 4 * 2,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=2,
                            kwargs={"pretrained": path}),
            save_dir=str(tmp_path / "ck"),
        )
        p2 = t.get_model().init_fn(jax.random.PRNGKey(1))
        np.testing.assert_allclose(
            np.asarray(p2["wte"]), sd["transformer.wte.weight"], rtol=1e-6
        )


@pytest.mark.slow
class TestHFLogitsParity:
    def test_gpt2_logits_match_hf(self):
        """Build an HF GPT2LMHeadModel from config (random init, NO network),
        ingest its state dict, and compare logits token for token — the
        strongest offline proof the mapping is right."""
        import torch
        from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

        hf_cfg = HFConfig(
            vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=4,
            layer_norm_epsilon=1e-6,  # match flax nn.LayerNorm's default
            attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
        )
        torch.manual_seed(0)
        hf = GPT2LMHeadModel(hf_cfg).eval()
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

        import jax
        import jax.numpy as jnp

        # f32 compute: the default bf16 dtype adds ~1e-2 rounding noise that
        # would mask a real mapping bug behind a loose tolerance
        spec = build_gpt2("test-tiny", attention="dense", dtype=jnp.float32)
        params, unused = ingest.params_from_state_dict(sd, spec.config)
        ingest.validate_against(
            params,
            jax.eval_shape(lambda: spec.init_fn(jax.random.PRNGKey(0))),
        )

        tokens = np.arange(2 * 48, dtype=np.int64).reshape(2, 48) % 256
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        got = np.asarray(spec.apply_fn(params, tokens.astype(np.int32)))
        np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)
