"""Ring attention / sequence parallelism on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from saturn_tpu.ops.ring import ring_attention, sharded_lm_loss_terms


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


def dense_causal_attention(q, k, v):
    """fp32 reference: plain causal softmax attention."""
    B, H, T, D = q.shape
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / np.sqrt(D)
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


class TestRingAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_dense(self, devices8, sp):
        B, H, T, D = 2, 2, 32, 8
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
            for _ in range(3)
        )
        mesh = Mesh(np.array(devices8[:sp]), ("seq",))

        def local(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", axis_size=sp)

        mapped = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, None, "seq"), P(None, None, "seq"), P(None, None, "seq")),
            out_specs=P(None, None, "seq"),
            check_vma=False,
        )
        out = jax.jit(mapped)(q, k, v)
        ref = dense_causal_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_sharded_loss_matches_dense(self, devices8):
        """Boundary-label exchange must reproduce the dense shifted CE."""
        from saturn_tpu.models.loss import pretraining_loss

        sp, B, T, V = 4, 2, 16, 11
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(B, T, V)), dtype=jnp.float32)
        tokens = jnp.asarray(rng.integers(0, V, size=(B, T)), dtype=jnp.int32)
        mesh = Mesh(np.array(devices8[:sp]), ("seq",))

        def local(lg, tk):
            s, c = sharded_lm_loss_terms(lg, tk, axis_name="seq", axis_size=sp)
            return lax.psum(s, "seq") / lax.psum(c, "seq")

        mapped = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq")),
            out_specs=P(),
            check_vma=False,
        )
        got = float(jax.jit(mapped)(logits, tokens))
        want = float(pretraining_loss(logits, tokens))
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestRingTechnique:
    def test_search_execute_ckpt(self, tiny_task, devices8):
        from saturn_tpu.parallel.ring import RingSequenceParallel
        from tests.test_executors import run_search_and_execute

        run_search_and_execute(RingSequenceParallel(), tiny_task, devices8[:4])

    def test_ring_matches_dp_loss(self, tiny_task, devices8):
        """Sequence-parallel step must compute the same math as dense DP."""
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.ring import RingSequenceParallel

        dp, ring = DataParallel(), RingSequenceParallel()
        b_dp = dp.build(tiny_task, devices8[:2], {"remat": False})
        b_r = ring.build(tiny_task, devices8[:4], {"sp": 4, "remat": False})
        s_dp, s_r = b_dp.init(), b_r.init()
        batch = tiny_task.batch_at(0)
        _, l_dp = b_dp.step(s_dp, jax.device_put(batch, b_dp.batch_sharding))
        _, l_r = b_r.step(s_r, jax.device_put(batch, b_r.batch_sharding))
        np.testing.assert_allclose(float(l_dp), float(l_r), rtol=2e-2)

    def test_ring_rotary_matches_dense(self, devices8, tmp_path):
        """GPT-J (rotary) under sequence sharding: the per-shard position
        offsets (axis_index * Tc) must reproduce dense global positions."""
        from saturn_tpu import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.ring import RingSequenceParallel

        task = Task(
            get_model=lambda **kw: build_gpt2("gptj-test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=4, vocab_size=256, n_tokens=64 * 4 * 4
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=4),
            save_dir=str(tmp_path / "ckpts"),
        )
        spec = task.get_model()
        params = spec.init_fn(jax.random.PRNGKey(0))
        batch = task.batch_at(0)
        dense = float(pretraining_loss(spec.apply_fn(params, jnp.asarray(batch)), jnp.asarray(batch)))

        ring = RingSequenceParallel()
        b = ring.build(task, devices8[:4], {"sp": 4, "remat": False})
        # init with the same PRNGKey(0) → identical params → losses must match.
        state = b.init()
        _, loss = b.step(state, jax.device_put(batch, b.batch_sharding))
        np.testing.assert_allclose(float(loss), dense, rtol=2e-2)

    def test_infeasible_for_custom_loss(self, tiny_task, devices8):
        from saturn_tpu.parallel.ring import RingSequenceParallel

        tiny_task.loss_fn = lambda logits, tokens: logits.mean()
        params, t = RingSequenceParallel().search(tiny_task, devices8[:4], tid=0)
        assert params is None
