"""Cross-mesh checkpoint migration: save on one mesh shape, restore on another.

The elastic replanner's migration story rests on one property of the
checkpoint format: an npz holds full host arrays keyed by tree path, so
nothing about the writing mesh survives in the file. These tests prove the
round trip on the 8 virtual CPU devices — save sharded over an N-device
mesh, ``restore_sharded`` onto N/2 and 2N, parameters bitwise-equal after
gather (the ISSUE's topology-change acceptance shape).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from saturn_tpu.utils import checkpoint as ckpt

pytestmark = pytest.mark.resilience


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def make_state(mesh, with_step=True):
    """A small train-state-shaped pytree sharded over ``mesh``'s dp axis.

    ``with_step=False`` drops the scalar leaf — a single uniform
    ``P('dp')`` sharding is only valid over rank>=1 leaves (mixed-rank
    trees use the callable / pytree-of-shardings forms instead)."""
    sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    state = {
        "params": {
            "w": jax.device_put(
                jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4), sh
            ),
            "b": jax.device_put(jnp.linspace(-1.0, 1.0, 8), sh),
        },
        "opt": {"mu": jax.device_put(jnp.ones((8, 4)) * 0.25, sh)},
    }
    if with_step:
        state["step"] = jax.device_put(jnp.asarray(7, dtype=jnp.int32), rep)
    return state


def gathered(tree):
    return jax.tree_util.tree_map(lambda l: np.asarray(jax.device_get(l)), tree)


class TestCrossMeshRestore:
    @pytest.mark.parametrize("n_to", [2, 8])  # N/2 and 2N around a 4-dev save
    def test_roundtrip_onto_resized_mesh(self, tmp_path, n_to, devices8):
        src = make_state(mesh_of(4), with_step=False)
        path = str(tmp_path / "state.npz")
        ckpt.save(path, src)

        to_sh = NamedSharding(mesh_of(n_to), P("dp"))
        out = ckpt.restore_sharded(path, src, to_sh)
        for leaf in jax.tree_util.tree_leaves(out):
            assert leaf.sharding == to_sh
            assert len(leaf.sharding.device_set) == n_to
        want, got = gathered(src), gathered(out)
        for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(want)[0],
            jax.tree_util.tree_flatten_with_path(got)[0],
        ):
            assert a.tobytes() == b.tobytes(), kp  # bitwise-equal after gather

    def test_callable_sharding_rule(self, tmp_path, devices8):
        """Per-leaf rules: shard matrices, replicate scalars — the shape a
        technique's ``restore`` path actually needs after migration."""
        src = make_state(mesh_of(4))
        path = str(tmp_path / "state.npz")
        ckpt.save(path, src)

        mesh = mesh_of(2)

        def rule(tree_path, leaf):
            return NamedSharding(mesh, P("dp") if leaf.ndim else P())

        out = ckpt.restore_sharded(path, src, rule)
        assert out["step"].sharding == NamedSharding(mesh, P())
        assert out["params"]["w"].sharding == NamedSharding(mesh, P("dp"))
        np.testing.assert_array_equal(
            gathered(out)["params"]["w"], gathered(src)["params"]["w"]
        )

    def test_pytree_of_shardings(self, tmp_path, devices8):
        src = make_state(mesh_of(4))
        path = str(tmp_path / "state.npz")
        ckpt.save(path, src)

        mesh = mesh_of(8)
        shardings = jax.tree_util.tree_map(
            lambda l: NamedSharding(mesh, P("dp") if l.ndim else P()), src
        )
        out = ckpt.restore_sharded(path, src, shardings)
        assert len(out["opt"]["mu"].sharding.device_set) == 8
        np.testing.assert_array_equal(
            gathered(out)["opt"]["mu"], gathered(src)["opt"]["mu"]
        )

    def test_restore_sharded_joins_async_write(self, tmp_path, devices8):
        """A migration racing an in-flight async save must see the full
        checkpoint (restore_sharded goes through the same join point)."""
        src = make_state(mesh_of(4), with_step=False)
        path = str(tmp_path / "state.npz")
        ckpt.save_async(path, src)
        out = ckpt.restore_sharded(
            path, src, NamedSharding(mesh_of(2), P("dp"))
        )
        np.testing.assert_array_equal(
            gathered(out)["params"]["b"], gathered(src)["params"]["b"]
        )
