"""Model zoo tests: shapes, loss, scanned-stack structure, remat equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.models.gpt2 import PRESETS, build_gpt2, config_for
from saturn_tpu.models.loss import pretraining_loss


@pytest.fixture(scope="module")
def tiny_spec():
    return build_gpt2("test-tiny")


def check_causality(spec):
    """Changing a future token must not change past logits."""
    params = spec.init_fn(jax.random.PRNGKey(0))
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0, 255)
    t2 = t1.at[0, 40].set((t1[0, 40] + 1) % 255)
    l1 = spec.apply_fn(params, t1)
    l2 = spec.apply_fn(params, t2)
    np.testing.assert_allclose(
        np.asarray(l1[0, :40]), np.asarray(l2[0, :40]), rtol=2e-3, atol=2e-3
    )
    assert not np.allclose(np.asarray(l1[0, 40:]), np.asarray(l2[0, 40:]))


def check_trains(spec):
    """5 adam steps on a fixed batch must reduce the loss."""
    import optax

    params = spec.init_fn(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, 255)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        loss, g = jax.value_and_grad(
            lambda p: pretraining_loss(spec.apply_fn(p, tokens), tokens)
        )(params)
        up, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, up), opt, loss

    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


class TestGPT2:
    def test_presets_exist(self):
        for name in ("gpt2-small", "gpt2-medium", "gpt2-large", "gpt2-xl", "gptj-6b"):
            assert name in PRESETS

    def test_forward_shape(self, tiny_spec):
        cfg = tiny_spec.config
        params = tiny_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, cfg.seq_len), dtype=jnp.int32)
        logits = tiny_spec.apply_fn(params, tokens)
        assert logits.shape == (2, cfg.seq_len, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_scanned_block_stack(self, tiny_spec):
        """Blocks must be one stacked pytree with a leading layer axis —
        the property pipeline/FSDP sharding relies on."""
        cfg = tiny_spec.config
        shapes = tiny_spec.abstract_init()
        assert "blocks" in shapes
        qkv = shapes["blocks"]["qkv"]["kernel"]
        assert qkv.shape == (cfg.n_layers, cfg.d_model, 3 * cfg.d_model)

    def test_abstract_init_matches_real(self, tiny_spec):
        shapes = tiny_spec.abstract_init()
        params = tiny_spec.init_fn(jax.random.PRNGKey(0))
        real_shapes = jax.tree.map(lambda x: x.shape, params)
        abs_shapes = jax.tree.map(lambda x: x.shape, shapes)
        assert real_shapes == abs_shapes

    def test_remat_same_output(self):
        spec = build_gpt2("test-tiny", remat=False)
        spec_r = build_gpt2("test-tiny", remat=True)
        params = spec.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 255)
        a = spec.apply_fn(params, tokens)
        b = spec_r.apply_fn(params, tokens)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)

    def test_causality(self, tiny_spec):
        check_causality(tiny_spec)

    def test_loss_decreases_under_sgd(self, tiny_spec):
        check_trains(tiny_spec)

    def test_config_validation(self):
        with pytest.raises(KeyError):
            config_for("no-such-model")


@pytest.mark.slow
class TestGPTJ:
    """Rotary + parallel-residual family (reference ``GPTJ.py:44-79,392-424``)."""

    @pytest.fixture(scope="class")
    def gptj_spec(self):
        from saturn_tpu.models.gpt2 import build_gptj

        return build_gptj("gptj-test-tiny")

    def test_rotary_is_relative(self):
        """Rotary q·k scores must depend only on relative position."""
        from saturn_tpu.models.gpt2 import apply_rotary, rotary_sin_cos

        rd = 8
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((1, 1, 1, rd)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 1, 1, rd)), jnp.float32)

        def score(qpos, kpos):
            sq, cq = rotary_sin_cos(jnp.asarray([qpos]), rd)
            sk, ck = rotary_sin_cos(jnp.asarray([kpos]), rd)
            qr = apply_rotary(q, sq, cq, rd)
            kr = apply_rotary(k, sk, ck, rd)
            return float(jnp.sum(qr * kr))

        np.testing.assert_allclose(score(7, 3), score(19, 15), rtol=1e-5)
        assert abs(score(7, 3) - score(7, 5)) > 1e-6

    def test_no_learned_positions(self, gptj_spec):
        shapes = gptj_spec.abstract_init()
        assert "wpe" not in shapes
        # parallel residual: one LayerNorm per block, no ln_2
        assert "ln_2" not in shapes["blocks"]

    def test_forward_and_causality(self, gptj_spec):
        cfg = gptj_spec.config
        params = gptj_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jnp.zeros((1, cfg.seq_len), dtype=jnp.int32)
        assert gptj_spec.apply_fn(params, tokens).shape == (
            1, cfg.seq_len, cfg.vocab_size,
        )
        check_causality(gptj_spec)

    def test_position_sensitivity(self, gptj_spec):
        """Swapping two prefix tokens must change later logits: without
        positions, attention over the prefix is permutation-invariant, so this
        only passes if rotary actually injects order."""
        cfg = gptj_spec.config
        params = gptj_spec.init_fn(jax.random.PRNGKey(0))
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, cfg.seq_len), 0, 255)
        t2 = t1.at[0, 0].set(t1[0, 1]).at[0, 1].set(t1[0, 0])
        assert int(t1[0, 0]) != int(t1[0, 1])
        l1 = gptj_spec.apply_fn(params, t1)
        l2 = gptj_spec.apply_fn(params, t2)
        assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]), atol=1e-4)

    def test_trains(self, gptj_spec):
        check_trains(gptj_spec)


@pytest.mark.slow
class TestLlama:
    """Llama-class family (RMSNorm + SwiGLU + grouped-query attention) —
    beyond the reference zoo; same scanned-stack ModelSpec contract, so
    every technique applies unchanged."""

    @pytest.fixture(scope="class")
    def llama_spec(self):
        from saturn_tpu.models.gpt2 import build_llama

        return build_llama("llama-test-tiny")

    def test_param_shapes(self, llama_spec):
        cfg = llama_spec.config
        shapes = llama_spec.abstract_init()
        assert "wpe" not in shapes  # rotary
        blocks = shapes["blocks"]
        # GQA: fused qkv out dim = D + 2 * kv_heads * head_dim
        kv_dim = cfg.n_kv_heads * cfg.head_dim
        assert blocks["qkv"]["kernel"].shape == (
            cfg.n_layers, cfg.d_model, cfg.d_model + 2 * kv_dim,
        )
        # SwiGLU: separate gate/up projections (TP column rule shards each
        # output dim, keeping gate_i/up_i on one shard)
        assert blocks["mlp_gate"]["kernel"].shape == (
            cfg.n_layers, cfg.d_model, cfg.ff_dim,
        )
        assert blocks["mlp_in"]["kernel"].shape == (
            cfg.n_layers, cfg.d_model, cfg.ff_dim,
        )
        # RMSNorm has scale only, no bias
        assert set(blocks["ln_1"]) == {"scale"}
        assert set(shapes["ln_f"]) == {"scale"}

    def test_forward_and_causality(self, llama_spec):
        cfg = llama_spec.config
        params = llama_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jnp.zeros((1, cfg.seq_len), dtype=jnp.int32)
        assert llama_spec.apply_fn(params, tokens).shape == (
            1, cfg.seq_len, cfg.vocab_size,
        )
        check_causality(llama_spec)

    def test_trains(self, llama_spec):
        check_trains(llama_spec)

    def test_gqa_matches_mha_when_groups_equal(self):
        """n_kv_heads == n_heads must behave like (and shape like) MHA
        through the GQA codepath's repeat factor of 1."""
        from saturn_tpu.models.gpt2 import build_llama

        spec = build_llama("llama-test-tiny", n_kv_heads=4)  # == n_heads
        cfg = spec.config
        shapes = spec.abstract_init()
        assert shapes["blocks"]["qkv"]["kernel"].shape == (
            cfg.n_layers, cfg.d_model, 3 * cfg.d_model,
        )
        check_trains(spec)

    def test_fused_loss_matches_logits_path(self, llama_spec):
        from saturn_tpu.models.loss import pretraining_loss

        assert llama_spec.fused_loss_fn is not None
        params = llama_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, llama_spec.config.seq_len), 0,
            llama_spec.config.vocab_size,
        ).astype(jnp.int32)
        ref = pretraining_loss(llama_spec.apply_fn(params, tokens), tokens)
        got = llama_spec.fused_loss_fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4)

    def test_invalid_kv_heads_rejected(self):
        from saturn_tpu.models.gpt2 import build_llama

        with pytest.raises(ValueError, match="n_kv_heads"):
            build_llama("llama-test-tiny", n_kv_heads=3)  # doesn't divide 4

    def test_dp_executor_runs(self, llama_spec, tmp_path, devices8):
        """One dp step end to end — the family plugs into the executors."""
        from saturn_tpu import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_llama
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.dp import DataParallel

        task = Task(
            get_model=lambda **kw: build_llama("llama-test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=2),
            save_dir=str(tmp_path / "ckpts"),
        )
        dp = DataParallel()
        bundle = dp.build(task, devices8[:2], {"remat": False})
        state = bundle.init()
        batch = jax.device_put(task.batch_at(0), bundle.batch_sharding)
        state, loss = bundle.step(state, batch)
        assert np.isfinite(float(jax.device_get(loss)))

    def test_flash_gqa_branch_matches_dense(self):
        """attention='flash' runs the Pallas kernel (interpret mode on CPU)
        through the Block's skip-repeat GQA branch — grouped k/v feed the
        kernel directly. Same params, must match the dense build."""
        from saturn_tpu.models.gpt2 import build_llama

        dense = build_llama("llama-test-tiny", attention="dense")
        flash = build_llama("llama-test-tiny", attention="flash")
        params = dense.init_fn(jax.random.PRNGKey(0))
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (2, dense.config.seq_len), 0,
            dense.config.vocab_size,
        ).astype(jnp.int32)
        l_d = dense.apply_fn(params, toks)
        l_f = flash.apply_fn(params, toks)
        np.testing.assert_allclose(np.asarray(l_f), np.asarray(l_d),
                                   rtol=2e-2, atol=2e-2)

    def test_tp_executor_runs(self, tmp_path, devices8):
        """Megatron TP on GQA+SwiGLU: the column rule shards qkv, mlp_gate
        and mlp_in output dims so silu(gate)*up stays shard-local."""
        from saturn_tpu import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_llama
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.tp import TensorParallel

        task = Task(
            get_model=lambda **kw: build_llama("llama-test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=2),
            save_dir=str(tmp_path / "ckpts"),
        )
        tp = TensorParallel()
        bundle = tp.build(task, devices8[:2], {"tp": 2, "remat": False})
        state = bundle.init()
        batch = jax.device_put(task.batch_at(0), bundle.batch_sharding)
        state, loss = bundle.step(state, batch)
        assert np.isfinite(float(jax.device_get(loss)))


def test_scan_unroll_matches_plain_scan():
    """unroll is a scheduling knob: same params tree, same outputs up to
    bf16 fusion-order rounding (~1 ulp — unrolling reorders XLA fusions)."""
    import jax
    import numpy as np

    from saturn_tpu.models.gpt2 import build_gpt2

    s1 = build_gpt2("test-tiny", scan_unroll=1)
    s2 = build_gpt2("test-tiny", scan_unroll=2)
    p = s1.init_fn(jax.random.PRNGKey(0))
    p2 = s2.init_fn(jax.random.PRNGKey(0))
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    toks = s1.config.example_inputs(2)
    np.testing.assert_allclose(
        np.asarray(s1.apply_fn(p, toks)), np.asarray(s2.apply_fn(p, toks)),
        rtol=2e-2, atol=1e-2,
    )
