"""Fused linear-cross-entropy numerics vs the dense oracle (interpret mode).

Mirrors tests/test_flash.py's strategy: the Pallas kernel can't lower on the
CPU test mesh, so correctness runs in interpret mode against
``dense_linear_cross_entropy`` (plain XLA ops), fwd and grads, including
ignore-index masking and a non-block-multiple vocab (pad-column masking).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.ops.ce import (
    dense_linear_cross_entropy,
    fused_linear_cross_entropy,
)


def _case(n=128, d=64, v=256, masked=8, seed=0, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = (jax.random.normal(k1, (n, d)) * 0.5).astype(dtype)
    w = (jax.random.normal(k2, (v, d)) * 0.5).astype(jnp.float32)
    labels = jax.random.randint(k3, (n,), 0, v).astype(jnp.int32)
    if masked:
        labels = labels.at[-masked:].set(-1)
    return x, w, labels


class TestFusedCE:
    # 300: not a lane multiple — pads to 384 with block_v=128, exercising the
    # in-kernel pad-column masking the production vocab (50304 → 51200) hits
    @pytest.mark.parametrize("v", [256, 300])
    def test_matches_dense_fwd(self, v):
        x, w, labels = _case(v=v)
        ref = dense_linear_cross_entropy(x, w, labels)
        got = fused_linear_cross_entropy(
            x, w, labels, block_n=64, block_v=128, interpret=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3)

    # v=300 pads: the masked-column branch must also be gradient-correct
    @pytest.mark.parametrize("v", [256, 300])
    def test_matches_dense_grads(self, v):
        x, w, labels = _case(v=v)

        ref_gx, ref_gw = jax.grad(
            lambda x_, w_: dense_linear_cross_entropy(x_, w_, labels),
            argnums=(0, 1),
        )(x, w)
        got_gx, got_gw = jax.grad(
            lambda x_, w_: fused_linear_cross_entropy(
                x_, w_, labels, block_n=64, block_v=128, interpret=True
            ),
            argnums=(0, 1),
        )(x, w)
        # bf16 logits stash in the kernel bwd: tolerances match what XLA's
        # own bf16-stash CE backward exhibits (atol covers near-zero
        # elements whose relative error the stash inflates)
        np.testing.assert_allclose(np.asarray(got_gx), np.asarray(ref_gx),
                                   rtol=2e-2, atol=3e-4)
        np.testing.assert_allclose(np.asarray(got_gw), np.asarray(ref_gw),
                                   rtol=2e-2, atol=3e-4)


    # recompute mode: no logits stash; bwd re-derives score blocks from
    # x@W^T — the long-context memory mode must match the oracle too
    @pytest.mark.parametrize("v", [256, 300])
    def test_recompute_mode_matches_dense(self, v):
        x, w, labels = _case(v=v)
        ref = dense_linear_cross_entropy(x, w, labels)
        got = fused_linear_cross_entropy(
            x, w, labels, block_n=64, block_v=128, interpret=True,
            stash=False,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3)
        ref_gx, ref_gw = jax.grad(
            lambda x_, w_: dense_linear_cross_entropy(x_, w_, labels),
            argnums=(0, 1),
        )(x, w)
        got_gx, got_gw = jax.grad(
            lambda x_, w_: fused_linear_cross_entropy(
                x_, w_, labels, block_n=64, block_v=128, interpret=True,
                stash=False,
            ),
            argnums=(0, 1),
        )(x, w)
        # recompute keeps f32 scores in bwd (no bf16 stash), so tolerances
        # are tighter than the stash-mode test
        np.testing.assert_allclose(np.asarray(got_gx), np.asarray(ref_gx),
                                   rtol=2e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(got_gw), np.asarray(ref_gw),
                                   rtol=2e-3, atol=1e-5)

    # Auto block-picking at gpt2-large/-xl d_model (round-3 advisor finding):
    # (1<<20)//D is not 128-aligned for D in {1280, 1600}, and pre-fix
    # _padded_vocab padded Vp only to the larger block, so the fwd/dx grids
    # truncated — 128 real vocab columns dropped from the logsumexp at the
    # shipped gpt2-xl shapes (advisor repro: fused 31.845 vs dense 32.065 at
    # D=1280, V=2200). No explicit block_n/block_v here: this exercises the
    # V>=2048 auto branch end to end, both stash and recompute backwards.
    @pytest.mark.parametrize("d", [1280, 1600])
    @pytest.mark.parametrize("stash", [True, False])
    def test_auto_blocks_large_dmodel(self, d, stash):
        x, w, labels = _case(n=128, d=d, v=2200)
        ref = dense_linear_cross_entropy(x, w, labels)
        got = fused_linear_cross_entropy(
            x, w, labels, interpret=True, stash=stash
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3)
        ref_gx, ref_gw = jax.grad(
            lambda x_, w_: dense_linear_cross_entropy(x_, w_, labels),
            argnums=(0, 1),
        )(x, w)
        got_gx, got_gw = jax.grad(
            lambda x_, w_: fused_linear_cross_entropy(
                x_, w_, labels, interpret=True, stash=stash
            ),
            argnums=(0, 1),
        )(x, w)
        # stash mode quantizes logits to bf16; at D=1280/1600 the logit
        # magnitudes (~sqrt(D)/2 here) make the absolute quantization error
        # ~2e-3 on the grads — far below the pre-fix failure (dropped
        # columns shift the loss itself by 0.22)
        tol = dict(rtol=2e-2, atol=3e-3) if stash else dict(rtol=2e-3,
                                                            atol=1e-5)
        np.testing.assert_allclose(np.asarray(got_gx), np.asarray(ref_gx),
                                   **tol)
        np.testing.assert_allclose(np.asarray(got_gw), np.asarray(ref_gw),
                                   **tol)

    def test_auto_vocab_blocks_are_lane_aligned(self):
        """Whatever the auto-picker chooses must be a multiple of the TPU's
        128-lane tile and must tile the padded vocab exactly."""
        from saturn_tpu.ops import ce as ce_mod

        for d in (768, 1024, 1280, 1600, 2048, 4096):
            bv_dw = ce_mod._auto_bv_dw(d)
            assert bv_dw % 128 == 0
            vp = ce_mod._padded_vocab(50304, (512, 512, 512, bv_dw))
            assert vp % 512 == 0 and vp % bv_dw == 0 and vp >= 50304

    def test_masked_tokens_zero_grad(self):
        x, w, labels = _case(masked=16)
        gx = jax.grad(
            lambda x_: fused_linear_cross_entropy(
                x_, w, labels, block_n=64, block_v=128, interpret=True
            )
        )(x)
        np.testing.assert_allclose(np.asarray(gx[-16:]), 0.0, atol=1e-7)

    def test_batch_shaped_input(self):
        x, w, labels = _case(n=128)
        ref = fused_linear_cross_entropy(
            x, w, labels, block_n=64, block_v=128, interpret=True
        )
        got = fused_linear_cross_entropy(
            x.reshape(2, 64, -1), w, labels.reshape(2, 64),
            block_n=64, block_v=128, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)

    def test_fallback_on_cpu(self):
        # production path (interpret=None) on the CPU mesh: dense fallback,
        # same value as the oracle exactly
        x, w, labels = _case()
        got = fused_linear_cross_entropy(x, w, labels)
        ref = dense_linear_cross_entropy(x, w, labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)

    def test_rejects_nonnegative_ignore_index(self):
        x, w, labels = _case()
        with pytest.raises(ValueError):
            fused_linear_cross_entropy(x, w, labels, ignore_index=0)


class TestModelFusedLoss:
    """The model-level fused objective equals pretraining_loss∘apply_fn."""

    def test_gpt2_fused_loss_matches_logits_path(self):
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss

        spec = build_gpt2("test-tiny")
        assert spec.fused_loss_fn is not None
        params = spec.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, spec.config.seq_len), 0,
            spec.config.vocab_size,
        ).astype(jnp.int32)
        ref = pretraining_loss(spec.apply_fn(params, tokens), tokens)
        got = spec.fused_loss_fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4)

    def test_moe_and_seq_parallel_have_no_fused_loss(self):
        from saturn_tpu.models.gpt2 import build_gpt2

        assert build_gpt2("moe-test-tiny").fused_loss_fn is None
        assert build_gpt2("test-tiny", seq_axis="sp",
                          seq_axis_size=2).fused_loss_fn is None

    def test_executor_step_routes_through_fused(self, monkeypatch):
        """step_fns_from_forward picks the fused path for standard tasks."""
        import saturn_tpu.models.gpt2 as gpt2_mod
        from saturn_tpu.core.task import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.dp import DataParallel

        calls = {"fused": 0}
        spec = build_gpt2("test-tiny")
        orig = spec.fused_loss_fn

        def counting_fused(params, tokens):
            calls["fused"] += 1
            return orig(params, tokens)

        spec.fused_loss_fn = counting_fused
        task = Task(
            get_model=lambda **kw: spec,
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=2, vocab_size=256,
                n_tokens=64 * 2 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=2),
            name="fused-route",
        )
        tech = DataParallel()
        init_state, train_step = tech.make_step_fns(
            spec, task, {"remat": False}, None, task.get_dataset()
        )
        params = spec.init_fn(jax.random.PRNGKey(0))
        jax.eval_shape(
            lambda p, b: train_step({"params": p,
                                     "opt_state": task.hparams.make_optimizer().init(p),
                                     "step": jnp.zeros((), jnp.int32)}, b),
            params, jnp.zeros((2, 64), jnp.int32),
        )
        assert calls["fused"] >= 1  # traced during step construction

    def test_tp_keeps_logits_path(self):
        """TP's vocab-sharded head must not route through the fused kernel."""
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.tp import TensorParallel

        assert DataParallel().fused_loss_ok
        assert not TensorParallel().fused_loss_ok

    def test_explicit_bad_block_n_falls_back_to_dense(self):
        # N=128 not divisible by block_n=48: must not truncate the grid —
        # the wrapper falls back to the dense computation (exact oracle)
        x, w, labels = _case(n=128)
        got = fused_linear_cross_entropy(
            x, w, labels, block_n=48, block_v=128, interpret=True
        )
        ref = dense_linear_cross_entropy(x, w, labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)

    def test_bert_fused_mlm_matches_logits_path(self):
        from saturn_tpu.models.bert import build_bert, mlm_loss

        spec = build_bert("bert-test-tiny")
        assert spec.fused_loss_fn is not None
        assert spec.fused_loss_objective == "mlm"
        params = spec.init_fn(jax.random.PRNGKey(0))
        # reserved top id (the [MASK] token) must not occur in data
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, spec.config.seq_len), 0,
            spec.config.vocab_size - 1,
        ).astype(jnp.int32)
        ref = mlm_loss(spec.apply_fn(params, tokens), tokens)
        got = spec.fused_loss_fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4)

    def test_objective_tag_mismatch_keeps_logits_path(self):
        """A BERT spec driven with pretraining_loss must NOT take the fused
        MLM path — the tags differ, so the executor uses the logits path."""
        from saturn_tpu.models.bert import build_bert
        from saturn_tpu.models.loss import pretraining_loss

        spec = build_bert("bert-test-tiny")
        assert pretraining_loss.supports_fused_head == "causal-lm"
        assert spec.fused_loss_objective == "mlm"

    @staticmethod
    def _mesh_gate_case(technique, mesh_devices):
        from jax.sharding import Mesh
        from saturn_tpu.core.task import HParams, Task
        from saturn_tpu.data.lm_dataset import make_lm_dataset
        from saturn_tpu.models.gpt2 import build_gpt2
        from saturn_tpu.models.loss import pretraining_loss

        calls = {"fused": 0, "parts": 0}
        spec = build_gpt2("test-tiny")
        orig, orig_parts = spec.fused_loss_fn, spec.fused_loss_parts_fn

        def counting_fused(params, tokens):
            calls["fused"] += 1
            return orig(params, tokens)

        def counting_parts(params, tokens):
            calls["parts"] += 1
            return orig_parts(params, tokens)

        spec.fused_loss_fn = counting_fused
        spec.fused_loss_parts_fn = counting_parts
        task = Task(
            get_model=lambda **kw: spec,
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=2, vocab_size=256,
                n_tokens=64 * 2 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=2),
            name="fused-mesh-gate",
        )
        mesh = Mesh(
            np.array(mesh_devices).reshape(len(mesh_devices)), ("data",)
        )
        init_state, train_step = technique.make_step_fns(
            spec, task, {"remat": False}, mesh, task.get_dataset()
        )
        params = spec.init_fn(jax.random.PRNGKey(0))
        jax.eval_shape(
            lambda p, b: train_step({"params": p,
                                     "opt_state": task.hparams.make_optimizer().init(p),
                                     "step": jnp.zeros((), jnp.int32)}, b),
            params, jnp.zeros((2, 64), jnp.int32),
        )
        return calls

    def test_multi_device_fsdp_keeps_logits_path(self):
        """fsdp shards params (incl. the vocab-dim wte), so multi-chip
        blocks must not route through the fused kernel — a pallas_call has
        no GSPMD partitioning rule (round-3 review finding)."""
        from saturn_tpu.parallel.fsdp import FSDP

        calls = self._mesh_gate_case(FSDP(), jax.devices()[:2])
        assert calls == {"fused": 0, "parts": 0}

    def test_multi_device_dp_routes_fused_parts(self):
        """dp (replicated params, batch-sharded) runs the fused loss on
        multi-chip blocks through the shard_map sum/count wrapper."""
        from saturn_tpu.parallel.dp import DataParallel

        calls = self._mesh_gate_case(DataParallel(), jax.devices()[:2])
        assert calls["parts"] >= 1 and calls["fused"] == 0

    @pytest.mark.slow
    def test_dp_sharded_fused_loss_matches_unsharded(self):
        """The psum'd (sum, count) mean over 2 batch shards equals the
        single-program fused mean."""
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from saturn_tpu.models.gpt2 import build_gpt2

        spec = build_gpt2("test-tiny")
        params = spec.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, spec.config.seq_len), 0,
            spec.config.vocab_size,
        ).astype(jnp.int32)
        ref = spec.fused_loss_fn(params, tokens)

        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("data",))

        def local(p, b):
            s, c = spec.fused_loss_parts_fn(p, b)
            return (jax.lax.psum(s, ("data",))
                    / jnp.maximum(jax.lax.psum(c, ("data",)), 1))

        got = shard_map(
            local, mesh=mesh, in_specs=(P(), P("data")), out_specs=P()
        )(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5)

        # Gradients through shard_map with replicated params (the psum
        # transpose): must match the unsharded fused grads (round-3 advisor
        # low finding — value-only coverage). On CPU the kernel falls back
        # to dense, so the TPU-pallas-under-shard_map case stays a chip-run
        # checklist item (BASELINE.md).
        ref_val, ref_grads = jax.value_and_grad(spec.fused_loss_fn)(
            params, tokens
        )
        got_val, got_grads = jax.value_and_grad(
            shard_map(local, mesh=mesh, in_specs=(P(), P("data")),
                      out_specs=P())
        )(params, tokens)
        np.testing.assert_allclose(np.asarray(got_val), np.asarray(ref_val),
                                   rtol=1e-5)
        flat_ref = jax.tree_util.tree_leaves(ref_grads)
        flat_got = jax.tree_util.tree_leaves(got_grads)
        assert len(flat_ref) == len(flat_got)
        # f32 reduction order differs between the psum'd shards and the
        # single program; observed agreement is ~2.4e-4 absolute
        for a, b in zip(flat_got, flat_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=4e-4)
