"""Differential oracle for the memlens liveness model: the static
per-device HBM peak must land within a calibrated band of what XLA's own
``memory_analysis()`` reports for the same step function, for every
built-in SPMD technique.

Each of the six strategies (dp/fsdp/tp/ep/ring/ulysses) is analyzed twice:

* **statically** — ``trace_step`` -> abstract jaxpr -> the memlens
  :class:`LivenessInterpreter` (no devices, no compile);
* **for real** — the same step jitted with the traced input shardings and
  ``donate_argnums=(0,)`` (the dispatch contract the profile models),
  compiled for 4 virtual CPU devices, and the peak taken from
  ``utils.timing.hbm_bytes_required`` (temp + argument + output - alias).

The comparable quantity is the *peak*, not a buffer-by-buffer match: XLA
legally fuses temporaries out of existence, schedules frees earlier than
linear-scan liveness, and pads for layout. Calibrated on this image the
static/compiled ratio sits at dp 0.71, fsdp 0.64, tp 1.01, ep 0.92,
ring 0.70, ulysses 0.67. The gate is a ratio in [0.4, 2.0] — wide enough
for scheduling slack, tight enough that a broken propagation rule (which
typically double-counts or drops whole state trees, i.e. >=4x) fails.

The fused ``lax.scan`` window (K>1) is held to the same band against the
real fused program, and the donation model is cross-checked: compiling a
step WITHOUT donation must raise the compiled peak exactly where memlens's
SAT-M003 pass predicts a missed donation.
"""

import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec

from saturn_tpu.analysis.memlens import liveness
from saturn_tpu.analysis.memlens import passes as ml_passes
from saturn_tpu.core.mesh import make_submesh
from saturn_tpu.utils.timing import hbm_bytes_required

pytestmark = pytest.mark.analysis

SIZE = 4

#: static peak / compiled peak must land here (see module doc)
PEAK_RATIO = (0.4, 2.0)

TECHNIQUES = ["dp", "fsdp", "tp", "ep", "ring", "ulysses"]


@pytest.fixture()
def moe_task(tmp_path):
    """The MoE sibling of ``tiny_task`` — required by the 'ep' technique."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    return Task(
        get_model=lambda **kw: build_gpt2("moe-test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 2),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "moe-ckpts"),
    )


def _technique(name):
    from saturn_tpu import library as lib

    if not lib.registered_names():
        lib.register_default_library()
    cls = lib.retrieve(name)
    return cls() if isinstance(cls, type) else cls


def _harness(name, task, devices):
    """(traced dict, mesh, train_step, state shardings, batch sharding)."""
    tech = _technique(name)
    config = tech.candidate_configs(task, SIZE)[0]
    traced = tech.trace_step(task, devices, config)

    axis_names, axis_sizes = tech.mesh_spec(SIZE, task, config)
    mesh = make_submesh(devices, axis_names, axis_sizes)
    spec = task.get_model(**tech._model_overrides(config)) \
        if hasattr(tech, "_model_overrides") else task.get_model()
    ds = task.get_dataset()
    _, train_step = tech.make_step_fns(spec, task, config, mesh, ds)

    state_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s if s is not None else PartitionSpec()),
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    batch_sh = NamedSharding(mesh, traced["batch_spec"])
    return traced, mesh, train_step, state_sh, batch_sh


def _compiled_peak(train_step, state_sh, batch_sh, traced, donate=(0,)):
    compiled = (
        jax.jit(train_step, in_shardings=(state_sh, batch_sh),
                donate_argnums=donate)
        .lower(traced["state_shapes"], traced["batch_sds"])
        .compile()
    )
    return hbm_bytes_required(compiled)


# --------------------------------------------------------------------------
# the differential gate
# --------------------------------------------------------------------------
@pytest.mark.parametrize("name", TECHNIQUES)
def test_static_peak_matches_compiled(name, tiny_task, moe_task, devices8):
    task = moe_task if name == "ep" else tiny_task
    traced, _, train_step, state_sh, batch_sh = _harness(
        name, task, devices8[:SIZE])

    profile = liveness.analyze(traced)
    assert profile.peak_bytes > 0, f"{name}: empty static profile"
    assert profile.persistent_bytes > 0, f"{name}: no resident state"

    compiled_peak = _compiled_peak(train_step, state_sh, batch_sh, traced)
    if compiled_peak == 0:
        pytest.skip("memory_analysis unavailable on this backend")

    ratio = profile.peak_bytes / compiled_peak
    lo, hi = PEAK_RATIO
    assert lo <= ratio <= hi, (
        f"{name}: static {profile.peak_bytes}B vs compiled {compiled_peak}B "
        f"(ratio {ratio:.2f} outside [{lo}, {hi}]) — "
        f"contributors={profile.peak_contributors[:3]}"
    )
    # the drift auditor must agree these two are within its gate
    assert ml_passes.audit_point(
        profile.peak_bytes, compiled_peak, name, SIZE) is None


def test_fused_window_peak_matches_compiled(tiny_task, devices8):
    """The K>1 ``lax.scan`` path: K stacked batch shards join the peak."""
    K = 3
    traced, mesh, train_step, state_sh, batch_sh = _harness(
        "dp", tiny_task, devices8[:SIZE])

    def multi_step(state, window):
        return jax.lax.scan(train_step, state, window)

    batch_sds = traced["batch_sds"]
    window_sds = jax.ShapeDtypeStruct((K, *batch_sds.shape), batch_sds.dtype)
    stacked_sh = NamedSharding(
        mesh, PartitionSpec(None, *(traced["batch_spec"] or ())))
    compiled = (
        jax.jit(multi_step, in_shardings=(state_sh, stacked_sh),
                donate_argnums=(0, 1))
        .lower(traced["state_shapes"], window_sds)
        .compile()
    )
    compiled_peak = hbm_bytes_required(compiled)
    if compiled_peak == 0:
        pytest.skip("memory_analysis unavailable on this backend")

    profile = liveness.analyze(traced, window=K)
    p1 = liveness.analyze(traced, window=1)
    assert profile.peak_bytes > p1.peak_bytes  # the window costs memory

    ratio = profile.peak_bytes / compiled_peak
    lo, hi = PEAK_RATIO
    assert lo <= ratio <= hi, (
        f"fused K={K}: static {profile.peak_bytes}B vs compiled "
        f"{compiled_peak}B (ratio {ratio:.2f} outside [{lo}, {hi}])"
    )


def test_donation_delta_where_sat_m003_predicts_it(tiny_task, devices8):
    """Where memlens flags a missed donation, XLA's compiled peak must
    actually drop once the donation is added — the M003 counterexample is
    real aliasing, not a shape coincidence."""
    traced, _, train_step, state_sh, batch_sh = _harness(
        "dp", tiny_task, devices8[:SIZE])

    # static side: the undonated-state profile flags the missed donations
    undonated = liveness.analyze_closed(
        traced["jaxpr"],
        _in_specs(traced),
        dict(traced["mesh_axes"]),
        donated=[False] * (len(_in_specs(traced))),
        n_state_in=len(_in_specs(traced)) - 1,
        n_state_out=len(_in_specs(traced)) - 1,
    )
    assert undonated.missed_donations, "M003 should fire without donation"

    # compiled side: the donated program needs strictly fewer bytes
    peak_donated = _compiled_peak(train_step, state_sh, batch_sh, traced,
                                  donate=(0,))
    peak_plain = _compiled_peak(train_step, state_sh, batch_sh, traced,
                                donate=())
    if peak_donated == 0 or peak_plain == 0:
        pytest.skip("memory_analysis unavailable on this backend")
    assert peak_donated < peak_plain

    # and the static model agrees on the direction (equality is legal: when
    # mid-backward transients dominate, donation moves end-of-step residency
    # but not the global peak)
    donated_profile = liveness.analyze(traced)
    assert donated_profile.peak_bytes <= undonated.peak_bytes
    assert not donated_profile.missed_donations


def _in_specs(traced):
    from jax.tree_util import tree_leaves

    state_leaves = tree_leaves(traced["state_shapes"])
    spec_leaves = tree_leaves(
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    specs = [
        liveness._from_pspec(ps, len(getattr(leaf, "shape", ())))
        for leaf, ps in zip(state_leaves, spec_leaves)
    ]
    specs.append(liveness._from_pspec(
        traced["batch_spec"], len(traced["batch_sds"].shape)))
    return specs
