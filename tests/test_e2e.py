"""End-to-end integration: search -> solve -> orchestrate on the CPU mesh.

The TPU-native analog of the reference's install-verification E2E
(``examples/wikitext103/simple-verification.py:33-111``): register
techniques, build a small task sweep, profile it, and orchestrate to
completion — here with a tiny GPT-2 on 8 virtual devices so it runs on any
host.
"""

import numpy as np
import pytest

import saturn_tpu
from saturn_tpu import HParams, Task, library
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.utils import checkpoint as ckpt_mod


def make_task(tmp_path, name, lr, batch_count=8):
    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=lr, batch_count=batch_count),
        chip_range=[4],
        name=name,
        save_dir=str(tmp_path / "ckpts"),
    )


@pytest.mark.slow
def test_search_then_orchestrate(tmp_path, devices8):
    """The canonical driver flow (``WikiText103.py:49-106``): register ->
    search -> orchestrate; both tasks train to completion with checkpoints."""
    topo = SliceTopology(devices8)
    library.register_default_library()
    tasks = [
        make_task(tmp_path, "sweep-lr3", lr=1e-3),
        make_task(tmp_path, "sweep-lr4", lr=1e-4),
    ]
    saturn_tpu.search(tasks, technique_names=["dp"], topology=topo)

    for t in tasks:
        feas = t.feasible_strategies()
        assert 4 in feas, f"no feasible 4-chip strategy for {t.name}"
        assert feas[4].per_batch_time > 0

    saturn_tpu.orchestrate(tasks, interval=30.0, topology=topo, solver_time_limit=5.0)

    for t in tasks:
        assert t.total_batches == 0
        assert t.has_ckpt()
        state = ckpt_mod.load_arrays(t.ckpt_path)
        assert state["step"] == 8  # all batches ran exactly once


@pytest.mark.slow
def test_parallel_trials_fill_strategies(tmp_path, devices8):
    """Concurrent same-size trials on disjoint blocks (the reference's Ray
    fan-out, ``PerformanceEvaluator.py:74-84``) must fill the same strategy
    table shape as the sequential path."""
    topo = SliceTopology(devices8)
    library.register_default_library()
    t_par = make_task(tmp_path, "par", lr=1e-3)
    t_par.chip_range = [1, 2]  # several disjoint blocks exist for each size
    saturn_tpu.search(
        [t_par], technique_names=["dp", "fsdp"], topology=topo,
        parallel_trials=4,
    )
    feas = t_par.feasible_strategies()
    assert set(feas) == {1, 2}
    for s in feas.values():
        assert s.per_batch_time > 0
        assert s.runtime > 0
