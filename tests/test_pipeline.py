"""Pipeline executor: schedule correctness vs. plain forward, and E2E execute.

The key invariant: the GPipe schedule is a *re-scheduling* of the same math —
for identical params and batch, the pipelined loss must equal the single
program loss (up to dtype noise), and one optimizer step must produce the
same loss trajectory as the DP executor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.parallel.pp import Pipeline


def test_pipeline_loss_matches_dense(tiny_task, devices8):
    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False}
    bundle = pp.build(tiny_task, devices8, config)
    state = bundle.init()
    batch = jax.device_put(tiny_task.get_dataset().batch(0), bundle.batch_sharding)
    _, pp_loss = bundle.step(state, batch)

    dp = DataParallel()
    dbundle = dp.build(tiny_task, devices8, {"remat": False})
    dstate = dbundle.init()
    dbatch = jax.device_put(tiny_task.get_dataset().batch(0), dbundle.batch_sharding)
    _, dp_loss = dbundle.step(dstate, dbatch)

    np.testing.assert_allclose(
        float(jax.device_get(pp_loss)), float(jax.device_get(dp_loss)),
        rtol=2e-2,
    )


def test_pipeline_multi_step_trains(tiny_task, devices8):
    pp = Pipeline()
    bundle = pp.build(tiny_task, devices8, {"stages": 2, "microbatches": 2, "remat": True})
    state = bundle.init()
    losses = []
    for i in range(4):
        batch = jax.device_put(
            tiny_task.get_dataset().batch(0), bundle.batch_sharding
        )
        state, loss = bundle.step(state, batch)
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], f"loss did not drop: {losses}"


def test_pipeline_candidate_configs(tiny_task):
    pp = Pipeline()
    grid = pp.candidate_configs(tiny_task, 8)
    assert grid, "tiny task (2 layers, batch 8) should admit pipeline configs"
    for cfg in grid:
        assert cfg["microbatches"] % cfg["stages"] == 0
        assert 2 % cfg["stages"] == 0  # n_layers divisible


def test_pipeline_execute_and_resume(tiny_task, devices8):
    from saturn_tpu.core.strategy import Strategy

    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False}
    tiny_task.strategies[8] = Strategy(
        executor=pp, apportionment=8, params=config, runtime=1.0, per_batch_time=0.1
    )
    tiny_task.select_strategy(8)
    pp.execute(tiny_task, devices8, tid=0, override_batch_count=2)
    assert tiny_task.has_ckpt()
    # resume restores step count and continues under the same technique
    pp.execute(tiny_task, devices8, tid=0, override_batch_count=1)
    from saturn_tpu.utils import checkpoint as ckpt

    bundle = pp.build(tiny_task, devices8, config)
    host = ckpt.restore(tiny_task.ckpt_path, bundle.state_shapes)
    assert int(host["step"]) == 3
