"""Pipeline executor: schedule correctness vs. plain forward, and E2E execute.

The key invariant: the GPipe schedule is a *re-scheduling* of the same math —
for identical params and batch, the pipelined loss must equal the single
program loss (up to dtype noise), and one optimizer step must produce the
same loss trajectory as the DP executor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.parallel.pp import Pipeline


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


def test_pipeline_loss_matches_dense(tiny_task, devices8):
    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False}
    bundle = pp.build(tiny_task, devices8, config)
    state = bundle.init()
    batch = jax.device_put(tiny_task.get_dataset().batch(0), bundle.batch_sharding)
    _, pp_loss = bundle.step(state, batch)

    dp = DataParallel()
    dbundle = dp.build(tiny_task, devices8, {"remat": False})
    dstate = dbundle.init()
    dbatch = jax.device_put(tiny_task.get_dataset().batch(0), dbundle.batch_sharding)
    _, dp_loss = dbundle.step(dstate, dbatch)

    np.testing.assert_allclose(
        float(jax.device_get(pp_loss)), float(jax.device_get(dp_loss)),
        rtol=2e-2,
    )


def test_pipeline_multi_step_trains(tiny_task, devices8):
    pp = Pipeline()
    bundle = pp.build(tiny_task, devices8, {"stages": 2, "microbatches": 2, "remat": True})
    state = bundle.init()
    losses = []
    for i in range(4):
        batch = jax.device_put(
            tiny_task.get_dataset().batch(0), bundle.batch_sharding
        )
        state, loss = bundle.step(state, batch)
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], f"loss did not drop: {losses}"


def test_pipeline_candidate_configs(tiny_task):
    pp = Pipeline()
    grid = pp.candidate_configs(tiny_task, 8)
    assert grid, "tiny task (2 layers, batch 8) should admit pipeline configs"
    for cfg in grid:
        assert cfg["microbatches"] % cfg["stages"] == 0
        assert 2 % cfg["stages"] == 0  # n_layers divisible


def _span_maxcost(costs, spans):
    out, i = [], 0
    for s in spans:
        out.append(sum(costs[i:i + s]))
        i += s
    return max(out)


def test_balance_stages_beats_even_split():
    """The DP (reference balance_by_time analog) minimizes the bottleneck
    stage — on uneven costs its split strictly beats the even one."""
    from saturn_tpu.ops.pipeline import balance_stages

    costs = [4, 1, 1, 1, 1, 1]
    spans = balance_stages(costs, 2)
    assert len(spans) == 2 and sum(spans) == 6 and min(spans) >= 1
    assert _span_maxcost(costs, spans) == 5      # [4,1 | 1,1,1,1]
    assert _span_maxcost(costs, (3, 3)) == 6     # even split is worse
    # Max-cost tie between (2,4) and (1,5): the tie-break must take the
    # smaller longest span — n_max drives padded memory and scan length.
    assert spans == (2, 4)


def test_balance_stages_never_worse_than_even(seed_count=30):
    """Property: on random cost vectors the DP's bottleneck cost is <= the
    even split's (when an even split exists), and spans always partition."""
    from saturn_tpu.ops.pipeline import balance_stages

    rng = np.random.default_rng(11)
    for _ in range(seed_count):
        S = int(rng.integers(2, 5))
        L = S * int(rng.integers(1, 5))
        costs = rng.uniform(0.5, 10.0, size=L).tolist()
        spans = balance_stages(costs, S)
        assert len(spans) == S and sum(spans) == L and min(spans) >= 1
        even = (L // S,) * S
        assert _span_maxcost(costs, spans) <= _span_maxcost(costs, even) + 1e-9


def test_balance_stages_uniform_indivisible():
    from saturn_tpu.ops.pipeline import balance_stages

    spans = balance_stages([1.0] * 6, 4)
    assert sorted(spans) == [1, 1, 2, 2]
    with pytest.raises(ValueError):
        balance_stages([1.0, 1.0], 3)  # more stages than layers


def test_uneven_spans_match_dp(tmp_path, devices8):
    """A 3-layer trunk over 2 stages (spans 2+1 via the padded schedule)
    computes the same loss as the DP executor — the re-scheduling
    invariant extended to unequal spans."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    task = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", n_layers=3, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "ckpts"),
    )
    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False,
              "spans": (2, 1)}
    bundle = pp.build(task, devices8, config)
    state = bundle.init()
    batch = jax.device_put(task.get_dataset().batch(0),
                           bundle.batch_sharding)
    _, pp_loss = bundle.step(state, batch)

    dp = DataParallel()
    dbundle = dp.build(task, devices8, {"remat": False})
    dstate = dbundle.init()
    dbatch = jax.device_put(task.get_dataset().batch(0),
                            dbundle.batch_sharding)
    _, dp_loss = dbundle.step(dstate, dbatch)

    np.testing.assert_allclose(
        float(jax.device_get(pp_loss)), float(jax.device_get(dp_loss)),
        rtol=2e-2,
    )


def test_candidate_configs_indivisible_stack(tmp_path):
    """Pre-round-4, a layer count the stage count doesn't divide silently
    produced zero pp candidates; now balanced spans make it feasible."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    task = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", n_layers=3, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "ckpts"),
    )
    grid = Pipeline().candidate_configs(task, 8)
    assert grid, "3-layer stack should admit pp via balanced spans"
    for cfg in grid:
        if cfg["stages"] == 2:
            assert sorted(cfg["spans"]) == [1, 2]  # either order is optimal


def test_candidate_configs_layer_costs(tmp_path):
    """A layer_costs hint drives cost-balanced (not count-balanced)
    boundaries, like the reference's balance_by_time."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    def get_model(**kw):
        spec = build_gpt2("test-tiny", n_layers=4, **kw)
        spec.hints["layer_costs"] = [4.0, 1.0, 1.0, 1.0]
        return spec

    task = Task(
        get_model=get_model,
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "ckpts"),
    )
    grid = Pipeline().candidate_configs(task, 8)
    two_stage = [c for c in grid if c["stages"] == 2]
    assert two_stage
    costs = [4.0, 1.0, 1.0, 1.0]
    for cfg in two_stage:
        spans = tuple(cfg["spans"])
        assert spans == (1, 3)  # [4 | 1,1,1] max 4 beats even [5, 2]
        assert _span_maxcost(costs, spans) < _span_maxcost(costs, (2, 2))


def test_pipeline_execute_and_resume(tiny_task, devices8):
    from saturn_tpu.core.strategy import Strategy

    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False}
    tiny_task.strategies[8] = Strategy(
        executor=pp, apportionment=8, params=config, runtime=1.0, per_batch_time=0.1
    )
    tiny_task.select_strategy(8)
    pp.execute(tiny_task, devices8, tid=0, override_batch_count=2)
    assert tiny_task.has_ckpt()
    # resume restores step count and continues under the same technique
    pp.execute(tiny_task, devices8, tid=0, override_batch_count=1)
    from saturn_tpu.utils import checkpoint as ckpt

    bundle = pp.build(tiny_task, devices8, config)
    host = ckpt.restore(tiny_task.ckpt_path, bundle.state_shapes)
    assert int(host["step"]) == 3
