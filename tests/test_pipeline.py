"""Pipeline executor: schedule correctness vs. plain forward, and E2E execute.

The key invariant: the GPipe schedule is a *re-scheduling* of the same math —
for identical params and batch, the pipelined loss must equal the single
program loss (up to dtype noise), and one optimizer step must produce the
same loss trajectory as the DP executor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.parallel.pp import Pipeline


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


def test_pipeline_loss_matches_dense(tiny_task, devices8):
    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False}
    bundle = pp.build(tiny_task, devices8, config)
    state = bundle.init()
    batch = jax.device_put(tiny_task.get_dataset().batch(0), bundle.batch_sharding)
    _, pp_loss = bundle.step(state, batch)

    dp = DataParallel()
    dbundle = dp.build(tiny_task, devices8, {"remat": False})
    dstate = dbundle.init()
    dbatch = jax.device_put(tiny_task.get_dataset().batch(0), dbundle.batch_sharding)
    _, dp_loss = dbundle.step(dstate, dbatch)

    np.testing.assert_allclose(
        float(jax.device_get(pp_loss)), float(jax.device_get(dp_loss)),
        rtol=2e-2,
    )


def test_pipeline_multi_step_trains(tiny_task, devices8):
    pp = Pipeline()
    bundle = pp.build(tiny_task, devices8, {"stages": 2, "microbatches": 2, "remat": True})
    state = bundle.init()
    losses = []
    for i in range(4):
        batch = jax.device_put(
            tiny_task.get_dataset().batch(0), bundle.batch_sharding
        )
        state, loss = bundle.step(state, batch)
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], f"loss did not drop: {losses}"


def test_pipeline_candidate_configs(tiny_task):
    pp = Pipeline()
    grid = pp.candidate_configs(tiny_task, 8)
    assert grid, "tiny task (2 layers, batch 8) should admit pipeline configs"
    for cfg in grid:
        assert cfg["microbatches"] % cfg["stages"] == 0
        assert 2 % cfg["stages"] == 0  # n_layers divisible


def _span_maxcost(costs, spans):
    out, i = [], 0
    for s in spans:
        out.append(sum(costs[i:i + s]))
        i += s
    return max(out)


def test_balance_stages_beats_even_split():
    """The DP (reference balance_by_time analog) minimizes the bottleneck
    stage — on uneven costs its split strictly beats the even one."""
    from saturn_tpu.ops.pipeline import balance_stages

    costs = [4, 1, 1, 1, 1, 1]
    spans = balance_stages(costs, 2)
    assert len(spans) == 2 and sum(spans) == 6 and min(spans) >= 1
    assert _span_maxcost(costs, spans) == 5      # [4,1 | 1,1,1,1]
    assert _span_maxcost(costs, (3, 3)) == 6     # even split is worse
    # Max-cost tie between (2,4) and (1,5): the tie-break must take the
    # smaller longest span — n_max drives padded memory and scan length.
    assert spans == (2, 4)


def test_balance_stages_never_worse_than_even(seed_count=30):
    """Property: on random cost vectors the DP's bottleneck cost is <= the
    even split's (when an even split exists), and spans always partition."""
    from saturn_tpu.ops.pipeline import balance_stages

    rng = np.random.default_rng(11)
    for _ in range(seed_count):
        S = int(rng.integers(2, 5))
        L = S * int(rng.integers(1, 5))
        costs = rng.uniform(0.5, 10.0, size=L).tolist()
        spans = balance_stages(costs, S)
        assert len(spans) == S and sum(spans) == L and min(spans) >= 1
        even = (L // S,) * S
        assert _span_maxcost(costs, spans) <= _span_maxcost(costs, even) + 1e-9


def test_balance_stages_uniform_indivisible():
    from saturn_tpu.ops.pipeline import balance_stages

    spans = balance_stages([1.0] * 6, 4)
    assert sorted(spans) == [1, 1, 2, 2]
    with pytest.raises(ValueError):
        balance_stages([1.0, 1.0], 3)  # more stages than layers


def test_uneven_spans_match_dp(tmp_path, devices8):
    """A 3-layer trunk over 2 stages (spans 2+1 via the padded schedule)
    computes the same loss as the DP executor — the re-scheduling
    invariant extended to unequal spans."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    task = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", n_layers=3, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "ckpts"),
    )
    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False,
              "spans": (2, 1)}
    bundle = pp.build(task, devices8, config)
    state = bundle.init()
    batch = jax.device_put(task.get_dataset().batch(0),
                           bundle.batch_sharding)
    _, pp_loss = bundle.step(state, batch)

    dp = DataParallel()
    dbundle = dp.build(task, devices8, {"remat": False})
    dstate = dbundle.init()
    dbatch = jax.device_put(task.get_dataset().batch(0),
                            dbundle.batch_sharding)
    _, dp_loss = dbundle.step(dstate, dbatch)

    np.testing.assert_allclose(
        float(jax.device_get(pp_loss)), float(jax.device_get(dp_loss)),
        rtol=2e-2,
    )


def test_candidate_configs_indivisible_stack(tmp_path):
    """Pre-round-4, a layer count the stage count doesn't divide silently
    produced zero pp candidates; now balanced spans make it feasible."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    task = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", n_layers=3, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "ckpts"),
    )
    grid = Pipeline().candidate_configs(task, 8)
    assert grid, "3-layer stack should admit pp via balanced spans"
    for cfg in grid:
        if cfg["stages"] == 2:
            assert sorted(cfg["spans"]) == [1, 2]  # either order is optimal


def test_candidate_configs_layer_costs(tmp_path):
    """A layer_costs hint drives cost-balanced (not count-balanced)
    boundaries, like the reference's balance_by_time."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    def get_model(**kw):
        spec = build_gpt2("test-tiny", n_layers=4, **kw)
        spec.hints["layer_costs"] = [4.0, 1.0, 1.0, 1.0]
        return spec

    task = Task(
        get_model=get_model,
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 4,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "ckpts"),
    )
    grid = Pipeline().candidate_configs(task, 8)
    two_stage = [c for c in grid if c["stages"] == 2]
    assert two_stage
    costs = [4.0, 1.0, 1.0, 1.0]
    for cfg in two_stage:
        spans = tuple(cfg["spans"])
        assert spans == (1, 3)  # [4 | 1,1,1] max 4 beats even [5, 2]
        assert _span_maxcost(costs, spans) < _span_maxcost(costs, (2, 2))


def test_pipeline_execute_and_resume(tiny_task, devices8):
    from saturn_tpu.core.strategy import Strategy

    pp = Pipeline()
    config = {"stages": 2, "microbatches": 2, "remat": False}
    tiny_task.strategies[8] = Strategy(
        executor=pp, apportionment=8, params=config, runtime=1.0, per_batch_time=0.1
    )
    tiny_task.select_strategy(8)
    pp.execute(tiny_task, devices8, tid=0, override_batch_count=2)
    assert tiny_task.has_ckpt()
    # resume restores step count and continues under the same technique
    pp.execute(tiny_task, devices8, tid=0, override_batch_count=1)
    from saturn_tpu.utils import checkpoint as ckpt

    bundle = pp.build(tiny_task, devices8, config)
    host = ckpt.restore(tiny_task.ckpt_path, bundle.state_shapes)
    assert int(host["step"]) == 3


# ------------------------------------------------------------------ round 20
# 1F1B: the staged schedule pair. Both orderings share one scan body (only
# the backward launch offset C differs), so their summed gradients must be
# BIT-identical — the acceptance bar for swapping schedules without
# perturbing a loss trajectory. Comparisons happen on host trees
# (jax.device_get) on purpose: this jax version's eager concatenate over
# stage-sharded leaves (ravel_pytree) resummes data-axis shards and
# manufactures phantom diffs.
def _toy_pipeline(L=4, DM=16, V=31, B=16, T=12, d=2):
    """Tiny embed->blocks->head model + a (data, stage) mesh slice."""
    from jax.sharding import Mesh

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "emb": jax.random.normal(k1, (V, DM)) * 0.02,
        "blocks": {
            "w": jax.random.normal(k2, (L, DM, DM)) * 0.1,
            "b": jnp.zeros((L, DM)),
        },
        "head": jax.random.normal(k3, (DM, V)) * 0.02,
    }
    tokens = jax.random.randint(k4, (B, T), 0, V)
    s = 8 // d
    devs = np.array(jax.devices()[:8]).reshape(d, s)
    mesh = Mesh(devs, ("data", "stage"))
    fns = dict(
        mesh=mesh,
        block_key="blocks",
        embed_fn=lambda other, tok: other["emb"][tok],
        block_fn=lambda lp, h: jnp.tanh(h @ lp["w"] + lp["b"]),
        head_fn=lambda other, h: h @ other["head"],
        loss_fn=lambda logits, tok: -jnp.mean(
            jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), tok[..., None], axis=-1
            )
        ),
    )

    def dense_loss(p, tok):
        h = fns["embed_fn"](p, tok)
        h, _ = jax.lax.scan(lambda hh, lp: (fns["block_fn"](lp, hh), None),
                            h, p["blocks"])
        return fns["loss_fn"](fns["head_fn"](p, h), tok)

    return params, tokens, fns, dense_loss


def _host_leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        jax.device_get(tree))]


def _assert_bitwise_equal(tree_a, tree_b):
    for a, b in zip(_host_leaves(tree_a), _host_leaves(tree_b)):
        assert a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def _assert_close(tree_a, tree_b, atol):
    for a, b in zip(_host_leaves(tree_a), _host_leaves(tree_b)):
        np.testing.assert_allclose(a, b, atol=atol, rtol=0)


@pytest.mark.parametrize("remat", [False, True])
def test_1f1b_bit_identical_to_staged_gpipe(devices8, remat):
    from saturn_tpu.ops.pipeline import staged_pipeline_loss_and_grads

    params, tokens, fns, dense_loss = _toy_pipeline(d=2)

    def run(schedule):
        f = jax.jit(lambda p, t: staged_pipeline_loss_and_grads(
            p, t, n_microbatches=4, schedule=schedule, remat=remat, **fns))
        return f(params, tokens)

    l1, g1 = run("1f1b")
    lg, gg = run("gpipe")
    assert float(jax.device_get(l1)) == float(jax.device_get(lg))
    _assert_bitwise_equal(g1, gg)
    # and both are the same math as the unpipelined reference
    l_ref, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
    np.testing.assert_allclose(
        float(jax.device_get(l1)), float(jax.device_get(l_ref)), atol=1e-5)
    _assert_close(g1, g_ref, atol=1e-6)


def test_1f1b_uneven_spans_bit_identical(devices8):
    """Unequal spans on a d>=2 mesh: pins the padded-span stack against the
    partitioner reshard bug (a concatenate-built operand entering shard_map
    partially sharded arrives summed over the data axis)."""
    from saturn_tpu.ops.pipeline import (
        balance_stages,
        staged_pipeline_loss_and_grads,
    )

    params, tokens, fns, dense_loss = _toy_pipeline(L=6, d=2)
    spans = balance_stages([1.0, 3.0, 1.0, 1.0, 1.0, 1.0], 4)
    assert max(spans) > min(spans)  # genuinely uneven

    def run(schedule):
        f = jax.jit(lambda p, t: staged_pipeline_loss_and_grads(
            p, t, n_microbatches=4, schedule=schedule, stage_spans=spans,
            **fns))
        return f(params, tokens)

    l1, g1 = run("1f1b")
    lg, gg = run("gpipe")
    assert float(jax.device_get(l1)) == float(jax.device_get(lg))
    _assert_bitwise_equal(g1, gg)
    _, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
    _assert_close(g1, g_ref, atol=1e-6)


def test_ad_gpipe_grads_match_dense_per_leaf(devices8):
    """Pins the psum-transpose fix: the AD GPipe path's summed grads equal
    the dense reference per-leaf (they were exactly S x too large when the
    replicated per-stage loss was differentiated through an outer psum)."""
    from saturn_tpu.ops.pipeline import pipeline_loss_and_grads

    params, tokens, fns, dense_loss = _toy_pipeline(d=2)
    f = jax.jit(lambda p, t: pipeline_loss_and_grads(
        p, t, n_microbatches=4, **fns))
    l_ad, g_ad = f(params, tokens)
    l_ref, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
    np.testing.assert_allclose(
        float(jax.device_get(l_ad)), float(jax.device_get(l_ref)), atol=1e-5)
    _assert_close(g_ad, g_ref, atol=1e-6)


def test_1f1b_microbatches_not_multiple_of_stages(devices8):
    """1F1B drops GPipe's M % S == 0 constraint: M=2 on S=4 stages runs and
    matches the dense reference, where the AD path refuses."""
    from saturn_tpu.ops.pipeline import (
        pipeline_loss_and_grads,
        staged_pipeline_loss_and_grads,
    )

    params, tokens, fns, dense_loss = _toy_pipeline(d=2)
    f = jax.jit(lambda p, t: staged_pipeline_loss_and_grads(
        p, t, n_microbatches=2, schedule="1f1b", **fns))
    _, g = f(params, tokens)
    _, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
    _assert_close(g, g_ref, atol=1e-6)
    with pytest.raises(ValueError, match="multiple"):
        jax.jit(lambda p, t: pipeline_loss_and_grads(
            p, t, n_microbatches=2, **fns))(params, tokens)


def test_1f1b_bundle_matches_gpipe_bundle(tiny_task, devices8):
    """Through the executor: schedule="1f1b" trains the same trajectory as
    schedule="gpipe" (the AD path), batch for batch."""
    pp = Pipeline()
    traj = {}
    for schedule in ("gpipe", "1f1b"):
        bundle = pp.build(tiny_task, devices8, {
            "stages": 2, "microbatches": 2, "schedule": schedule,
            "remat": False,
        })
        state = bundle.init()
        losses = []
        for i in range(3):
            batch = jax.device_put(
                tiny_task.get_dataset().batch(i), bundle.batch_sharding)
            state, loss = bundle.step(state, batch)
            losses.append(float(jax.device_get(loss)))
        traj[schedule] = losses
    np.testing.assert_allclose(traj["1f1b"], traj["gpipe"], rtol=1e-6)


def test_1f1b_mid_window_kill_and_resume(tmp_path, devices8):
    """A SimulatedKill while a 1F1B window is staging loses nothing durable:
    resume replays from the last checkpoint and lands on the same final
    state as an uninterrupted run, bit for bit."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.core.strategy import Strategy
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss
    from saturn_tpu.resilience import SimulatedKill
    from saturn_tpu.utils import checkpoint as ckpt

    config = {"stages": 2, "microbatches": 2, "schedule": "1f1b",
              "remat": False}

    def mk_task(save_dir):
        return Task(
            get_model=lambda **kw: build_gpt2("test-tiny", n_layers=2, **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 4,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=4),
            save_dir=str(save_dir),
        )

    def arm(task):
        pp = Pipeline()
        task.strategies[8] = Strategy(
            executor=pp, apportionment=8, params=config,
            runtime=1.0, per_batch_time=0.1,
        )
        task.select_strategy(8)
        return pp

    # --- reference: two clean 2-batch intervals (the engine advances the
    # data cursor between intervals; mirror that here)
    ref = mk_task(tmp_path / "ref")
    pp_ref = arm(ref)
    pp_ref.execute(ref, devices8, tid=0, override_batch_count=2)
    ref.reconfigure(2)
    pp_ref.execute(ref, devices8, tid=0, override_batch_count=2)

    # --- victim: interval 1 clean, interval 2 killed mid-window staging
    vic = mk_task(tmp_path / "vic")
    pp_vic = arm(vic)
    pp_vic.execute(vic, devices8, tid=1, override_batch_count=2)
    vic.reconfigure(2)
    assert vic.has_ckpt()

    orig_batch_at = vic.batch_at
    state = {"armed": True}

    def killing_batch_at(i):
        if state["armed"] and i == 3:
            raise SimulatedKill("mid-window staging kill")
        return orig_batch_at(i)

    vic.batch_at = killing_batch_at
    with pytest.raises(SimulatedKill):
        pp_vic.execute(vic, devices8, tid=1, override_batch_count=2)
    state["armed"] = False

    # the killed interval published nothing: the checkpoint still says step 2
    bundle = pp_vic.build(vic, devices8, config)
    host = ckpt.restore(vic.ckpt_path, bundle.state_shapes)
    assert int(host["step"]) == 2

    # resume replays batches 2..3 and converges with the reference
    pp_vic.execute(vic, devices8, tid=1, override_batch_count=2)
    final_vic = ckpt.restore(vic.ckpt_path, bundle.state_shapes)
    ref_bundle = pp_ref.build(ref, devices8, config)
    final_ref = ckpt.restore(ref.ckpt_path, ref_bundle.state_shapes)
    assert int(final_vic["step"]) == 4
    _assert_bitwise_equal(final_vic, final_ref)
