"""Pipeline schedule sweep + analytic bubble pricing (round 20).

Hardware-free: ``candidate_configs`` and the bubble/stash formulas are pure
Python, so the divisor stage sweep, the microbatch fallback, the
``schedule`` grid dimension, and the cross-slice ``stage_major`` gating all
get tier-1 coverage without compiling a single program (the staged programs
themselves are pinned in ``tests/test_pipeline.py``'s slow suite).
"""

from typing import Optional

import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.parallel.pp import Pipeline


class FakeDev:
    platform = "cpu"
    device_kind = "fake-cpu"
    process_index = 0


class _Spec:
    def __init__(self, n_layers):
        self.hints = {"pipeline": True}
        self.config = type("C", (), {"n_layers": n_layers})()
        self.apply_with_aux_fn = None  # no aux loss: pp-compatible


class _DS:
    def __init__(self, batch_size):
        self.batch_size = batch_size


class _Task:
    """candidate_configs-facing duck type: a model spec and a batch size."""

    def __init__(self, n_layers, batch_size):
        self._spec = _Spec(n_layers)
        self._ds = _DS(batch_size)

    def get_model(self, **kw):
        return self._spec

    def get_dataset(self):
        return self._ds


def _pp(topology: Optional[SliceTopology] = None) -> Pipeline:
    pp = Pipeline()
    if topology is not None:
        pp.topology = topology
    return pp


# -------------------------------------------------------------- stage sweep
def test_divisor_stage_sweep_covers_non_powers_of_two():
    """A 6-device block admits s=2, s=3 AND s=6 — the old ``s <<= 1`` sweep
    never proposed the odd divisors."""
    grid = _pp().candidate_configs(_Task(n_layers=6, batch_size=24), 6)
    assert sorted({c["stages"] for c in grid}) == [2, 3, 6]


def test_stage_sweep_respects_layer_and_batch_limits():
    # stages never exceed layers...
    grid = _pp().candidate_configs(_Task(n_layers=2, batch_size=24), 8)
    assert {c["stages"] for c in grid} == {2}
    # ...and a data width that doesn't divide the batch is skipped
    grid = _pp().candidate_configs(_Task(n_layers=8, batch_size=6), 8)
    for c in grid:
        d = 8 // c["stages"]
        assert 6 % d == 0


def test_schedule_is_a_grid_dimension():
    grid = _pp().candidate_configs(_Task(n_layers=4, batch_size=16), 4)
    assert {c["schedule"] for c in grid} == {"gpipe", "1f1b"}
    # every config names its schedule explicitly — the trial runner times
    # both and realized cost picks, nothing is implied by omission
    assert all("schedule" in c for c in grid)


# ------------------------------------------------------ microbatch fallback
def test_microbatch_fallback_to_largest_divisor():
    """per-replica batch 6 at s=2: the preferred (8, 4, 2) ladder hits 2,
    but per-replica 9 at s=3 has no 12/6/3?  9 % 3 == 0 -> ladder works;
    use per-replica 10 at s=4 where none of 16/8/4 divide: the fallback
    finds the largest stage multiple that does."""
    # s=4, d=1, per_replica=10: gpipe ladder (16, 8, 4) all fail; the
    # stage-multiple fallback range (4, 8, 12, 16) also fails -> gpipe
    # absent at s=4, and the 1f1b fallback picks the largest divisor of 10
    # in [2, 16] -> 10.
    grid = _pp().candidate_configs(_Task(n_layers=4, batch_size=10), 4)
    four = [c for c in grid if c["stages"] == 4]
    assert four, "s=4 must survive via the 1f1b fallback"
    assert {c["schedule"] for c in four} == {"1f1b"}
    assert {c["microbatches"] for c in four} == {10}
    # s=2, d=2, per_replica=5: same story — gpipe has no multiple of 2
    # dividing 5, 1f1b takes m=5.
    two = [c for c in grid if c["stages"] == 2]
    assert {c["schedule"] for c in two} == {"1f1b"}
    assert {c["microbatches"] for c in two} == {5}


def test_microbatch_stage_multiple_fallback_for_gpipe():
    """s=4, per-replica 12: the (16, 8, 4) ladder hits 4 directly; but
    per-replica 24 at s=4 prefers 16? 24 % 16 != 0 -> ladder gives 8.
    The interesting case is per-replica 12 at s=6 (d=1): ladder (24, 12, 6)
    -> 12 and 6 divide; both schedules keep M % S == 0 candidates."""
    grid = _pp().candidate_configs(_Task(n_layers=6, batch_size=12), 6)
    six = [c for c in grid if c["stages"] == 6]
    for c in six:
        assert c["microbatches"] % c["stages"] == 0


# ------------------------------------------------- cross-slice stage layout
def test_stage_major_layout_gated_on_cross_slice_topology():
    task = _Task(n_layers=8, batch_size=16)
    # no topology stamped -> never proposed
    grid = _pp().candidate_configs(task, 8)
    assert all("layout" not in c for c in grid)
    # single-slice topology -> still never proposed
    topo = SliceTopology([FakeDev() for _ in range(8)], slice_size=8)
    grid = _pp(topo).candidate_configs(task, 8)
    assert all("layout" not in c for c in grid)
    # block larger than one slice -> stage_major rides along
    topo = SliceTopology([FakeDev() for _ in range(8)], slice_size=4)
    grid = _pp(topo).candidate_configs(task, 8)
    layouts = {c.get("layout") for c in grid}
    assert layouts == {None, "stage_major"}


def test_stage_major_mesh_puts_stage_on_the_leading_axis():
    """stage_major flips the mesh so the stage axis is LEADING — with
    slice-major device order that is the axis whose hops cross slices, and
    shardflow's ``crossing_axes`` then prices stage ppermutes at DCN rate."""
    pp = _pp()
    axes, shape = pp.mesh_spec(8, None, {"stages": 4, "layout": "stage_major"})
    assert axes == ("stage", "data")
    assert shape == (4, 2)
    axes, shape = pp.mesh_spec(8, None, {"stages": 4})
    assert axes == ("data", "stage")
    assert shape == (2, 4)


# ------------------------------------------------------------ bubble pricing
def test_bubble_fraction_formulas():
    from saturn_tpu.ops.pipeline import schedule_bubble_fraction

    # GPipe: (S-1)/(M+S-1); 1F1B: (S-1)/(M+2(S-1))
    assert schedule_bubble_fraction("gpipe", 4, 4) == pytest.approx(3 / 7)
    assert schedule_bubble_fraction("1f1b", 4, 4) == pytest.approx(3 / 10)
    # 1F1B's bubble is never larger, and strictly smaller for S >= 2
    for s in (2, 3, 4, 8):
        for m in (s, 2 * s, 4 * s):
            g = schedule_bubble_fraction("gpipe", s, m)
            f = schedule_bubble_fraction("1f1b", s, m)
            assert f < g
    # degenerate single stage: no bubble either way
    assert schedule_bubble_fraction("gpipe", 1, 4) == 0.0
    assert schedule_bubble_fraction("1f1b", 1, 4) == 0.0


def test_config_bubble_fraction_reads_the_config():
    pp = _pp()
    gp = pp.config_bubble_fraction({"stages": 4, "microbatches": 8})
    f1 = pp.config_bubble_fraction(
        {"stages": 4, "microbatches": 8, "schedule": "1f1b"})
    assert gp == pytest.approx(3 / 11)   # schedule defaults to gpipe
    assert f1 == pytest.approx(3 / 14)
    assert f1 < gp


def test_base_technique_bubble_is_zero():
    """Non-pipeline techniques have no schedule bubble: the base hook the
    evaluator calls at install time must return 0.0, keeping Strategy's
    default and the solver's host-only fillable fraction unchanged."""
    from saturn_tpu.parallel.dp import DataParallel

    assert DataParallel().config_bubble_fraction({"remat": True}) == 0.0
