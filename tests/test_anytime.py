"""Anytime tier-ladder tests: deadline races, tier equivalence vs the exact
MILP, verifier compliance on randomized instances, and the scaling smoke.

Hardware-free (solver consumes only numbers), same layer as
``test_solver.py``; the randomized-instance sweep reuses the
differential-oracle idiom from ``test_analysis_differential.py`` — generate
many random instances, run every tier, and hold each output to the same
``plan_verifier`` gate the orchestrator enforces at adoption.
"""

import json
import os
import random
import subprocess
import sys

import pytest

from saturn_tpu.analysis import plan_verifier
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.solver import anytime, milp
from saturn_tpu.utils import metrics

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class FakeTask:
    """Solver-facing duck type: only .name and .feasible_strategies()."""

    def __init__(self, name, runtimes):
        self.name = name
        self.strategies = {
            g: Strategy(object(), g, {}, rt, 0.1) for g, rt in runtimes.items()
        }

    def feasible_strategies(self):
        return self.strategies


def rand_tasks(rng, n, prefix="t"):
    """Amdahl-shaped random instances: bigger slices are faster but with
    diminishing returns, like the profiled strategies the solver really sees."""
    out = []
    for i in range(n):
        base = rng.uniform(2.0, 40.0)
        out.append(FakeTask(f"{prefix}{i}", {
            2: base,
            4: base * rng.uniform(0.55, 0.8),
            8: base * rng.uniform(0.35, 0.6),
        }))
    return out


def verify(plan, tp, tasks):
    plan_verifier.verify_or_raise(plan, tp, tasks=tasks)


@pytest.mark.solver
class TestProbeCap:
    """Satellite: warm_schedule(insert_missing=) per-insertion search cap."""

    def _count_probes(self, monkeypatch, cap):
        tp = topo(8)
        rng = random.Random(3)
        old = rand_tasks(rng, 6)
        prev = milp.greedy_plan(old, tp)
        newcomers = rand_tasks(rng, 4, prefix="new")

        counts = {"n": 0}
        orig = milp.DeviceTimeline.earliest_free

        def counting(self, blk, dur):
            counts["n"] += 1
            return orig(self, blk, dur)

        # patched only around warm_schedule: earliest_free is exactly the
        # per-insertion probe (pinned tasks go through place(), not probes)
        monkeypatch.setattr(milp.DeviceTimeline, "earliest_free", counting)
        plan = milp.warm_schedule(old + newcomers, tp, prev,
                                  insert_missing=True,
                                  insertion_probe_cap=cap)
        monkeypatch.undo()
        return plan, counts["n"]

    def test_cap_bounds_probe_work(self, monkeypatch):
        uncapped, n_uncapped = self._count_probes(monkeypatch, None)
        capped, n_capped = self._count_probes(monkeypatch, 3)
        # 6 pinned re-placements (place() probes once each) are constant;
        # insertion work: 4 newcomers x (4+2+1=7 block slots) uncapped vs
        # 4 x cap=3 capped
        assert n_uncapped == 6 + 4 * 7
        assert n_capped == 6 + 4 * 3
        # the cap bounds work, never placement: every task still lands
        assert len(capped.assignments) == len(uncapped.assignments) == 10

    def test_cap_is_deterministic(self):
        tp = topo(8)
        rng = random.Random(5)
        old = rand_tasks(rng, 5)
        prev = milp.greedy_plan(old, tp)
        tasks = old + rand_tasks(rng, 5, prefix="new")
        a = milp.warm_schedule(tasks, tp, prev, insert_missing=True,
                               insertion_probe_cap=4)
        b = milp.warm_schedule(tasks, tp, prev, insert_missing=True,
                               insertion_probe_cap=4)
        assert {n: (x.apportionment, x.block.offset, x.start)
                for n, x in a.assignments.items()} == \
               {n: (x.apportionment, x.block.offset, x.start)
                for n, x in b.assignments.items()}

    def test_cap_never_strands_a_schedulable_task(self):
        tp = topo(8)
        rng = random.Random(7)
        old = rand_tasks(rng, 4)
        prev = milp.greedy_plan(old, tp)
        tasks = old + rand_tasks(rng, 6, prefix="new")
        plan = milp.warm_schedule(tasks, tp, prev, insert_missing=True,
                                  insertion_probe_cap=1)
        assert plan is not None
        assert set(plan.assignments) == {t.name for t in tasks}
        verify(plan, tp, tasks)


@pytest.mark.solver
class TestTierEquivalence:
    """On instances the exact MILP can solve, every richer tier stays within
    a bounded makespan ratio — the ladder degrades gracefully, not wildly."""

    EXACT_S = 2.0

    def _exact(self, tasks, tp):
        return milp.solve(tasks, tp, time_limit=self.EXACT_S)

    def test_tier0_incremental_matches_exact_structure(self):
        rng = random.Random(11)
        for k in range(4):
            tp = topo(8)
            tasks = rand_tasks(rng, rng.randint(6, 12), prefix=f"i{k}-")
            exact = self._exact(tasks, tp)
            p0 = anytime.incremental_plan(tasks, tp, exact)
            assert p0 is not None
            verify(p0, tp, tasks)
            # re-list-scheduling the exact structure costs only ordering slack
            assert p0.makespan <= exact.makespan * 1.5 + 8.0

    def test_tier1_partition_within_bound(self, monkeypatch):
        monkeypatch.setenv(anytime.PARTITION_MAX_ENV, "4")  # force stitching
        rng = random.Random(13)
        for k in range(3):
            tp = topo(8)
            tasks = rand_tasks(rng, 12, prefix=f"p{k}-")
            exact = self._exact(tasks, tp)
            p1 = anytime.partition_plan(tasks, tp, budget=3.0)
            assert p1 is not None
            verify(p1, tp, tasks)
            assert p1.makespan <= exact.makespan * 1.5 + 8.0

    def test_tier1_single_partition_is_exact(self):
        rng = random.Random(17)
        tp = topo(8)
        tasks = rand_tasks(rng, 6)
        exact = self._exact(tasks, tp)
        p1 = anytime.partition_plan(tasks, tp, budget=self.EXACT_S / 0.9)
        assert abs(p1.makespan - exact.makespan) <= 1e-6

    def test_tier2_lp_round_within_bound(self):
        rng = random.Random(19)
        for k in range(4):
            tp = topo(8)
            tasks = rand_tasks(rng, rng.randint(6, 12), prefix=f"l{k}-")
            exact = self._exact(tasks, tp)
            p2, lb = anytime.lp_round_plan(tasks, tp, seed=k)
            assert p2 is not None
            verify(p2, tp, tasks)
            assert p2.makespan <= exact.makespan * 2.0 + 8.0
            # the LP optimum is a true lower bound when it proved optimality
            if lb > 0:
                assert lb <= exact.makespan + 1e-6


@pytest.mark.solver
class TestRandomizedVerifierSweep:
    """500 random instances: every tier's output passes the adoption gate."""

    N = 500

    def test_all_tiers_verify(self):
        rng = random.Random(23)
        milp_budget_used = 0
        for k in range(self.N):
            tp = topo(8)
            tasks = rand_tasks(rng, rng.randint(2, 10), prefix=f"r{k}-")
            floor = anytime.fast_greedy_plan(tasks, tp)
            verify(floor, tp, tasks)
            p2, _ = anytime.lp_round_plan(tasks, tp, seed=k, rounds=2)
            assert p2 is not None
            verify(p2, tp, tasks)
            p0 = anytime.incremental_plan(tasks, tp, floor)
            assert p0 is not None
            verify(p0, tp, tasks)
            # stitch path with the budget-exhausted greedy rule (fast); the
            # MILP-in-partition variant is budgeted to a small subsample
            os.environ[anytime.PARTITION_MAX_ENV] = "3"
            try:
                if milp_budget_used < 5 and len(tasks) >= 6:
                    p1 = anytime.partition_plan(tasks, tp, budget=1.0)
                    milp_budget_used += 1
                else:
                    p1 = anytime.partition_plan(tasks, tp, budget=1e-6)
                assert p1 is not None
                verify(p1, tp, tasks)
            finally:
                del os.environ[anytime.PARTITION_MAX_ENV]

    def test_ladder_front_end_verifies_and_meets_deadline(self):
        rng = random.Random(29)
        prev = None
        for k in range(40):
            tp = topo(8)
            tasks = rand_tasks(rng, rng.randint(2, 10), prefix=f"f{k}-")
            plan, report = anytime.anytime_solve(tasks, tp, 0.5, previous=prev)
            verify(plan, tp, tasks)
            assert report.wall_s <= 0.5 + 0.1
            prev = plan


@pytest.mark.solver
class TestDeadlineLadder:
    def test_greedy_only_when_starved(self):
        """The floor fires iff the deadline can't afford any richer tier."""
        rng = random.Random(31)
        tp = topo(8)
        tasks = rand_tasks(rng, 400)
        _, starved = anytime.anytime_solve(tasks, tp, deadline=1e-3)
        assert starved.tier == 3
        assert starved.tiers_tried == [3]
        _, roomy = anytime.anytime_solve(tasks, tp, deadline=5.0)
        assert roomy.tier != 3

    def test_incremental_preferred_with_covering_previous(self):
        rng = random.Random(37)
        tp = topo(8)
        tasks = rand_tasks(rng, 300)
        first, _ = anytime.anytime_solve(tasks, tp, deadline=1.0)
        grown = tasks + rand_tasks(rng, 10, prefix="new")
        plan, report = anytime.anytime_solve(grown, tp, deadline=1.0,
                                             previous=first)
        assert 0 in report.tiers_tried
        assert report.n_loose == 10
        verify(plan, tp, grown)

    def test_deadline_env_override(self, monkeypatch):
        monkeypatch.setenv(anytime.DEADLINE_ENV, "0.25")
        assert anytime.resolve_deadline(3.0, 10.0) == 0.25
        monkeypatch.delenv(anytime.DEADLINE_ENV)
        assert anytime.resolve_deadline(3.0, 10.0) == 3.0
        assert anytime.resolve_deadline(None, 10.0) == 5.0
        assert anytime.resolve_deadline(None, None) == anytime._DEFAULT_DEADLINE

    def test_solver_tier_event_emitted(self, tmp_path):
        rng = random.Random(41)
        tp = topo(8)
        tasks = rand_tasks(rng, 8)
        mpath = str(tmp_path / "m.jsonl")
        with metrics.scoped(mpath):
            plan = anytime.anytime_resolve(tasks, tp, None, 1.0,
                                           deadline=1.0, source="test")
            anytime.anytime_resolve(tasks, tp, plan, 1.0, threshold=1e9,
                                    deadline=1.0, source="test")
        evs = metrics.read_events(mpath, kind="solver_tier")
        assert len(evs) == 2
        for ev in evs:
            assert ev["source"] == "test"
            assert ev["tier"] in anytime.TIER_NAMES
            assert ev["tier_name"] == anytime.TIER_NAMES[ev["tier"]]
            assert ev["n_tasks"] == 8
            assert ev["wall_s"] <= ev["deadline_s"] + 0.1
        assert evs[0]["outcome"] == "fresh"
        assert evs[1]["outcome"] == "slid"

    def test_cas_adopts_fresh_on_growth_and_shrink(self):
        rng = random.Random(43)
        tp = topo(8)
        tasks = rand_tasks(rng, 6)
        plan = anytime.anytime_resolve(tasks, tp, None, 1.0, deadline=1.0)
        grown = tasks + rand_tasks(rng, 2, prefix="g")
        p2 = anytime.anytime_resolve(grown, tp, plan, 1.0, deadline=1.0)
        assert p2.anytime.outcome == "fresh"
        assert set(p2.assignments) == {t.name for t in grown}
        p3 = anytime.anytime_resolve(tasks[:4], tp, p2, 1.0, deadline=1.0)
        assert p3.anytime.outcome == "fresh"
        assert set(p3.assignments) == {t.name for t in tasks[:4]}


@pytest.mark.solver
@pytest.mark.analysis
class TestSweepVerifier:
    """The O(N)-ish sweep verifier agrees with the exact analyzer on solver
    output and still catches planted races."""

    def test_sweep_accepts_all_tier_output(self):
        rng = random.Random(47)
        tp = topo(8)
        tasks = rand_tasks(rng, 30)
        for plan in (
            anytime.fast_greedy_plan(tasks, tp),
            anytime.lp_round_plan(tasks, tp, seed=1)[0],
        ):
            names = [t.name for t in tasks]
            exact = plan_verifier.launch_diagnostics(names, plan,
                                                     force_exact=True)
            sweep = plan_verifier.launch_diagnostics(names, plan,
                                                     force_sweep=True)
            assert [d.code for d in exact] == []
            assert [d.code for d in sweep] == []

    def test_sweep_catches_planted_race(self):
        rng = random.Random(53)
        tp = topo(8)
        tasks = rand_tasks(rng, 12)
        plan = anytime.fast_greedy_plan(tasks, tp)
        # Overlap two same-device tasks and sever their dependency edge.
        per_dev = {}
        for n, a in plan.assignments.items():
            per_dev.setdefault(a.block.offset, []).append(n)
        victims = next(v for v in per_dev.values() if len(v) >= 2)[:2]
        n1, n2 = victims
        a2 = plan.assignments[n2]
        plan.assignments[n2] = milp.Assignment(
            a2.apportionment, a2.block,
            plan.assignments[n1].start, a2.runtime)
        plan.dependencies = {
            n: [d for d in deps if {n, d} != {n1, n2}]
            for n, deps in plan.dependencies.items()
        }
        names = list(plan.assignments)
        codes = {d.code for d in plan_verifier.launch_diagnostics(
            names, plan, force_sweep=True)}
        assert "SAT-P001" in codes

    def test_chain_dependencies_are_race_sound(self):
        rng = random.Random(59)
        tp = topo(8)
        tasks = rand_tasks(rng, 300)
        plan = anytime.fast_greedy_plan(tasks, tp)
        assert len(plan.assignments) > anytime._CHAIN_DEP_N
        # chain edges (sparse) must satisfy the sweep race check
        diags = plan_verifier.launch_diagnostics(
            [t.name for t in tasks], plan, force_sweep=True)
        assert [d.code for d in diags] == []
        # and be far sparser than the dense pairwise form
        n_edges = sum(len(v) for v in plan.dependencies.values())
        assert n_edges < len(plan.assignments) * 8


@pytest.mark.solver
@pytest.mark.perf
class TestScalingSmoke:
    """The quick-mode scaling bench end-to-end: 500 jobs through the real
    gateway + service, zero deadline misses, schema-valid row."""

    def test_quick_mode_row(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "benchmarks",
                                          "solver_scaling.py")],
            capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, (r.stdout, r.stderr)
        row = json.loads(r.stdout.strip().splitlines()[-1])
        sys.path.insert(0, os.path.join(REPO, "benchmarks"))
        import bench_guard
        assert bench_guard.validate_solver_row(row) == []
        assert row["deadline_misses"] == 0
        assert row["quality_delta_pct"] <= 10.0
        assert row["resolves"] >= 3
