"""Round-19 sharded checkpoint format: manifest structure, zero-gather
save, cross-technique restore, the legacy-npz compat reader, crash
kill-points at the two commit edges, async keep-first error retention,
per-interval MFU telemetry, and the ``analysis ckpt`` CLI summary.

These complement ``test_ckpt_migration.py`` (cross-mesh resharding) by
pinning the FORMAT itself: what is on disk, what survives a torn write,
and what the consumers observe.
"""

import json
import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from saturn_tpu.utils import checkpoint as ckpt
from saturn_tpu.utils import metrics

pytestmark = pytest.mark.resilience


def mesh_of(n, axes=("dp",)):
    devs = np.array(jax.devices()[: int(np.prod([n]))])
    return Mesh(devs.reshape(n), axes)


def make_state(mesh):
    """Train-state-shaped tree: 2-d param, 1-d bias, 0-d step counter."""
    sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    return {
        "params": {
            "w": jax.device_put(
                jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4), sh
            ),
            "b": jax.device_put(jnp.linspace(-1.0, 1.0, 8), sh),
        },
        "step": jax.device_put(jnp.asarray(7, dtype=jnp.int32), rep),
    }


def host_tree(tree):
    return jax.tree_util.tree_map(
        lambda l: np.asarray(jax.device_get(l)), tree
    )


@pytest.fixture(autouse=True)
def _no_leaked_crash_barrier():
    yield
    ckpt.set_crash_barrier(None)


class TestManifestFormat:
    def test_manifest_and_shard_layout(self, tmp_path, devices8):
        state = make_state(mesh_of(4))
        path = str(tmp_path / "t.npz")
        ckpt.save(path, state)

        # logical path holds the JSON manifest, not a zip archive
        with open(path, "rb") as f:
            assert f.read(1) == b"{"
        with open(path) as f:
            man = json.load(f)
        assert man["format"] == ckpt.MANIFEST_FORMAT
        assert man["version"] == ckpt.MANIFEST_VERSION
        assert man["pspec_fingerprint"]
        assert set(man["leaves"]) == {"params/w", "params/b", "step"}
        w = man["leaves"]["params/w"]
        assert w["shape"] == [8, 4] and w["dtype"] == "float32"
        # a sharded leaf's shard table covers the full extent
        rows = sum(s["index"][0][1] - s["index"][0][0] for s in w["shards"])
        assert rows == 8
        # shard files sit next to the manifest and match the naming scheme
        shard_files = [
            n for n in os.listdir(tmp_path) if ckpt._SHARD_RE.search(n)
        ]
        assert shard_files, "no shard files written"
        for n in shard_files:
            assert n.startswith("t.npz.g")
            assert zipfile.is_zipfile(tmp_path / n)
        assert ckpt.verify(path)

    def test_cross_technique_chain_bit_identical(self, tmp_path, devices8):
        """dp -> fsdp-style resharded save -> tp-style columns: the bytes
        survive two migrations (per-leaf tobytes, the ISSUE acceptance)."""
        path = str(tmp_path / "t.npz")
        dp = make_state(mesh_of(4))
        want = host_tree(dp)
        ckpt.save(path, dp)

        # fsdp-style: shard over all 8 devices
        def fsdp_rule(p, sds):
            m = mesh_of(8)
            if sds.ndim and sds.shape[0] % 8 == 0:
                return NamedSharding(m, P("dp"))
            return NamedSharding(m, P())

        fsdp = ckpt.restore_sharded(path, dp, fsdp_rule)
        ckpt.save(path, fsdp)

        # tp-style: split the trailing axis instead
        def tp_rule(p, sds):
            m = Mesh(np.array(jax.devices()[:4]), ("tp",))
            if sds.ndim == 2 and sds.shape[1] % 4 == 0:
                return NamedSharding(m, P(None, "tp"))
            return NamedSharding(m, P())

        tp = ckpt.restore_sharded(path, dp, tp_rule)
        got = host_tree(tp)
        for key in ("params/w", "params/b", "step"):
            a = want["params"][key.split("/")[1]] if "/" in key else want[key]
            b = got["params"][key.split("/")[1]] if "/" in key else got[key]
            assert a.tobytes() == b.tobytes(), key

    def test_resave_garbage_collects_old_generation(self, tmp_path, devices8):
        state = make_state(mesh_of(4))
        path = str(tmp_path / "t.npz")
        ckpt.save(path, state)
        gen1 = {n for n in os.listdir(tmp_path) if ckpt._SHARD_RE.search(n)}
        ckpt.save(path, state)
        gen2 = {n for n in os.listdir(tmp_path) if ckpt._SHARD_RE.search(n)}
        assert gen1.isdisjoint(gen2), "stale generation not collected"
        assert ckpt.verify(path)

    def test_tampered_manifest_quarantined(self, tmp_path, devices8):
        state = make_state(mesh_of(4))
        path = str(tmp_path / "t.npz")
        ckpt.save(path, state)
        with open(path) as f:
            man = json.load(f)
        man["leaves"]["step"]["shape"] = [3]  # checksum now stale
        with open(path, "w") as f:
            json.dump(man, f)
        assert not ckpt.verify(path)
        with pytest.raises(ckpt.CheckpointCorruptError):
            ckpt.load_arrays(path)
        assert os.path.exists(path + ".corrupt")

    def test_missing_shard_file_quarantined(self, tmp_path, devices8):
        state = make_state(mesh_of(4))
        path = str(tmp_path / "t.npz")
        ckpt.save(path, state)
        victim = next(
            n for n in os.listdir(tmp_path) if ckpt._SHARD_RE.search(n)
        )
        os.unlink(tmp_path / victim)
        assert not ckpt.verify(path)
        with pytest.raises(ckpt.CheckpointCorruptError):
            ckpt.load_arrays(path)


class TestCompatReader:
    def test_legacy_single_file_restores(self, tmp_path, devices8):
        """Checkpoints written by the pre-round-19 allgather writer (one
        npz of full host arrays) must keep restoring."""
        path = str(tmp_path / "old.npz")
        arrays = {
            "params/w": np.arange(32, dtype=np.float32).reshape(8, 4),
            "step": np.asarray(5, dtype=np.int32),
        }
        with open(path, "wb") as f:
            np.savez(f, **arrays)

        loaded = ckpt.load_arrays(path)
        assert loaded["params/w"].tobytes() == arrays["params/w"].tobytes()

        template = {
            "params": {"w": jnp.zeros((8, 4), jnp.float32)},
            "step": jnp.asarray(0, jnp.int32),
        }
        out = ckpt.restore(path, template)
        assert int(out["step"]) == 5

        sh = NamedSharding(mesh_of(4), P())
        placed = ckpt.restore_sharded(path, template, sh)
        got = host_tree(placed)
        assert got["params"]["w"].tobytes() == arrays["params/w"].tobytes()


class TestAsyncErrorRetention:
    def test_keep_first_error_per_path(self, tmp_path, caplog):
        key = os.path.abspath(str(tmp_path / "x.npz"))
        first = RuntimeError("disk full")
        second = RuntimeError("later noise")
        ckpt._record_async_failure(key, key, first)
        with caplog.at_level("WARNING", logger="saturn_tpu.utils.checkpoint"):
            ckpt._record_async_failure(key, key, second)
        assert any("keeping first error" in r.getMessage()
                   for r in caplog.records)
        with pytest.raises(RuntimeError) as ei:
            ckpt.flush()
        assert ei.value.__cause__ is first

    def test_failed_async_write_surfaces_at_flush(self, tmp_path, devices8):
        state = make_state(mesh_of(2))
        # the "parent dir" is a regular file: the background commit's
        # makedirs fails deterministically (snapshot itself touches no disk)
        (tmp_path / "nodir").write_bytes(b"")
        target = str(tmp_path / "nodir" / "t.npz")
        ckpt.save_async(target, state)
        with pytest.raises(RuntimeError, match="async checkpoint write"):
            ckpt.flush()
        ckpt.flush()  # error consumed: the next flush is clean


@pytest.mark.crash
class TestCrashKillPoints:
    def _save_gen(self, path, mesh, fill):
        sh = NamedSharding(mesh, P("dp"))
        state = {"w": jax.device_put(
            jnp.full((8, 4), fill, jnp.float32), sh)}
        ckpt.save(path, state)
        return state

    def test_mid_shard_write_keeps_previous_generation(
            self, tmp_path, devices8):
        from saturn_tpu.resilience.crash import CrashInjector, SimulatedKill

        path = str(tmp_path / "t.npz")
        self._save_gen(path, mesh_of(4), 1.0)
        before = ckpt.load_arrays(path)["w"].tobytes()

        inj = CrashInjector("mid-shard-write")
        ckpt.set_crash_barrier(inj.barrier)
        with pytest.raises(SimulatedKill):
            self._save_gen(path, mesh_of(4), 2.0)
        ckpt.set_crash_barrier(None)

        # previous manifest + shard generation untouched and valid
        assert ckpt.verify(path)
        assert ckpt.load_arrays(path)["w"].tobytes() == before
        # no tmp litter from the torn write
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]

    def test_pre_manifest_rename_keeps_previous_manifest(
            self, tmp_path, devices8):
        from saturn_tpu.resilience.crash import CrashInjector, SimulatedKill

        path = str(tmp_path / "t.npz")
        self._save_gen(path, mesh_of(4), 1.0)
        before = ckpt.load_arrays(path)["w"].tobytes()

        # new-generation shard files may already be durable; the manifest
        # rename is THE commit point, so the old state must still win
        inj = CrashInjector("pre-manifest-rename")
        ckpt.set_crash_barrier(inj.barrier)
        with pytest.raises(SimulatedKill):
            self._save_gen(path, mesh_of(4), 2.0)
        ckpt.set_crash_barrier(None)

        assert ckpt.verify(path)
        assert ckpt.load_arrays(path)["w"].tobytes() == before

    def test_torn_shard_set_reconciles_to_previous_publication(
            self, tmp_path, devices8):
        """recovery.reconcile_checkpoints quarantines a manifest whose
        shard set is torn and falls back to the previous durable one —
        the zero-lost-jobs acceptance from the ISSUE."""
        from saturn_tpu.durability.recovery import reconcile_checkpoints

        old = str(tmp_path / "a" / "t.npz")
        new = str(tmp_path / "b" / "t.npz")
        os.makedirs(os.path.dirname(old))
        os.makedirs(os.path.dirname(new))
        self._save_gen(old, mesh_of(4), 1.0)
        self._save_gen(new, mesh_of(4), 2.0)
        # tear the newer publication: delete its shard file(s)
        for n in os.listdir(tmp_path / "b"):
            if ckpt._SHARD_RE.search(n):
                os.unlink(tmp_path / "b" / n)

        out = reconcile_checkpoints({"job": [old, new]})
        assert out == {"job": old}
        assert os.path.exists(new + ".corrupt")


class TestMfuTelemetry:
    def test_task_interval_reports_tflops_and_mfu(
            self, tiny_task, devices8, tmp_path):
        from saturn_tpu.core.strategy import Strategy
        from saturn_tpu.parallel.dp import DataParallel

        mpath = str(tmp_path / "metrics.jsonl")
        with metrics.scoped(mpath):
            tech = DataParallel()
            params, t = tech.search(tiny_task, devices8[:1], tid=0)
            tiny_task.strategies[1] = Strategy(tech, 1, params, 100.0, t)
            tiny_task.select_strategy(1)
            tech.execute(tiny_task, devices8[:1], tid=0,
                         override_batch_count=2)
        evs = [e for e in metrics.read_events(mpath)
               if e["kind"] == "task_interval"]
        assert evs, "no task_interval events emitted"
        for e in evs:
            assert "tflops" in e and "mfu" in e, e
            assert e["tflops"] > 0
            assert 0 < e["mfu"] < 1.5  # vs the default cpu-prior peak


class TestCkptCli:
    def test_ckpt_summary_json(self, tmp_path, devices8, capsys):
        from saturn_tpu.analysis.cli import main

        state = make_state(mesh_of(4))
        ckpt.save(str(tmp_path / "t.npz"), state)
        rc = main(["--json", "ckpt", str(tmp_path)])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert len(out["checkpoints"]) == 1
        row = out["checkpoints"][0]
        assert row["ok"] and row["format"] == "sharded-manifest"
        assert row["leaves"] == 3
        assert out["orphan_shards"] == []

    def test_ckpt_flags_corrupt_dir(self, tmp_path, devices8, capsys):
        from saturn_tpu.analysis.cli import main

        state = make_state(mesh_of(4))
        path = str(tmp_path / "t.npz")
        ckpt.save(path, state)
        for n in os.listdir(tmp_path):
            if ckpt._SHARD_RE.search(n):
                os.unlink(tmp_path / n)
        rc = main(["--json", "ckpt", str(tmp_path)])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert not out["checkpoints"][0]["ok"]
        # every shard file is gone but none were orphaned (they belonged
        # to the manifest); a stray unreferenced shard IS flagged
        (tmp_path / "t.npz.gdeadbeef.r9.npz").write_bytes(b"PK\x03\x04")
        main(["--json", "ckpt", str(tmp_path)])
        out2 = json.loads(capsys.readouterr().out)
        assert out2["orphan_shards"]
