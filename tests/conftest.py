"""Test harness: 8 virtual CPU devices (SURVEY.md §4's test-pyramid plan).

Multi-device behavior is tested without TPU hardware via XLA's host-platform
device emulation — the TPU-native analog of the reference's fake-8-GPUs solver
stub (``milp.py:57-62``), but as a proper fixture instead of a hardcoded flag.
Must run before jax initializes its backends, hence top of conftest.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
def _supports_collective_timeout_flag() -> bool:
    """Does this jaxlib's XLA know the collective-timeout flag?

    XLA FATALLY aborts on unknown XLA_FLAGS at first backend init
    (``parse_flags_from_env.cc``), which would take down the whole suite at
    the first test that touches a device — so probe in a subprocess first.
    The verdict is cached in a tmp sentinel keyed on the jaxlib version
    (the probe costs a ~3s jax import).
    """
    import json
    import subprocess
    import sys
    import tempfile

    import jaxlib.version

    sentinel = os.path.join(tempfile.gettempdir(), "saturn_xla_flag_probe.json")
    try:
        with open(sentinel) as f:
            rec = json.load(f)
        if rec.get("jaxlib") == jaxlib.version.__version__:
            return bool(rec["supported"])
    except (OSError, ValueError, KeyError):
        pass
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_cpu_collective_call_terminate_timeout_seconds=600"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        capture_output=True,
        env=env,
        timeout=120,
    )
    ok = r.returncode == 0
    try:
        tmp = f"{sentinel}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"jaxlib": jaxlib.version.__version__, "supported": ok}, f)
        os.replace(tmp, sentinel)
    except OSError:
        pass
    return ok


if (
    "collective_call_terminate_timeout" not in os.environ["XLA_FLAGS"]
    and _supports_collective_timeout_flag()
):
    # 8 emulated devices = 8 collective threads timesharing this host's ONE
    # core: XLA's default 40s cross-module-collective rendezvous abort
    # ("Termination timeout ... Exiting") fires spuriously under load
    # (observed on ppermute pipeline tests). Give stragglers 10 minutes.
    # NOTE the flag is baked into compiled programs: clear the persistent
    # cache below if it predates a change to this value.
    os.environ["XLA_FLAGS"] += (
        " --xla_cpu_collective_call_terminate_timeout_seconds=600"
    )
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

# The image's sitecustomize force-registers the axon TPU plugin and pins
# JAX_PLATFORMS=axon; the config update wins over the env var.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: OFF by default (opt in with
# SATURN_TPU_COMPILE_CACHE=1 for fast local re-runs). The cache dir gets
# written by execution contexts whose CPU feature detection differs
# (sandboxed vs not), and XLA:CPU loads mismatched entries anyway
# (cpu_aot_loader's "machine type doesn't match" warning) — executing wrong
# code that silently kills partition threads, wedging every later
# 8-partition collective program until the 600s watchdog SIGABRTs the suite
# at a timing-dependent pipeline/ring test. Cold compiles cost ~6 extra
# minutes; a poisoned cache costs the whole suite.
if os.environ.get("SATURN_TPU_COMPILE_CACHE"):
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


import numpy as np
import pytest


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def tiny_task(tmp_path):
    """A GPT-2 test-tiny task over a synthetic corpus — fast on CPU."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    def get_model(**kw):
        return build_gpt2("test-tiny", **kw)

    def get_loader():
        return make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8
        )

    return Task(
        get_model=get_model,
        get_dataloader=get_loader,
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=16),
        save_dir=str(tmp_path / "ckpts"),
    )


