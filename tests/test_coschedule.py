"""Cross-job co-scheduling (round 11): the solver's host-fraction co-location
term, the engine's interleave-aware group launcher, the condensed race guard,
the AOT executable cache, and the host-fraction plumbing.

The tentpole claim mirrors round 10's: interleaving two co-located jobs'
windows on a shared launcher is a pure wall-clock packing change — each
member's dispatch ORDER (and therefore its loss/checkpoint trajectory) is
identical to a solo run. ``TestTrajectoryEquivalence`` asserts that
bit-for-bit on real programs; everything else here is hardware-free fakes.
"""

import threading
import time

import numpy as np
import pytest

from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.executor import engine
from saturn_tpu.resilience.faults import PreemptedError
from saturn_tpu.solver.milp import (
    Assignment,
    Plan,
    coschedule_candidates,
    solve,
)

pytestmark = pytest.mark.coschedule


class FakeDev:
    platform = "cpu"
    device_kind = "fake-cpu"
    process_index = 0


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class FakeTask:
    def __init__(self, name, total_batches, sizes, tech, pbt=0.001, hf=0.0):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt,
                        host_fraction=hf)
            for g in sizes
        }
        self.selected_strategy = None
        self.realized = []  # per-batch feedback the launcher attributed

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length

    def note_realized_per_batch(self, per_batch):
        self.realized.append(per_batch)


class RecordingTech(BaseTechnique):
    """Plain execute-only technique (no generator support): in a co-schedule
    group it must take the sequential-fallback path."""

    name = "fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        time.sleep(self.per_batch * (override_batch_count or 1))
        with self.lock:
            self.calls.append(
                (task.name, len(devices), override_batch_count,
                 time.monotonic())
            )

    def search(self, task, devices, tid):
        return {}, self.per_batch


class GenTech(BaseTechnique):
    """Generator-capable fake: each unit optionally 'stages' (yields
    "waiting") before dispatching, mimicking a stage-bound job whose host
    phases the group launcher fills with a neighbor's windows."""

    name = "gen"
    supports_coschedule = True

    def __init__(self, log, stage_delay=0.0, fail_at=None):
        self.log = log  # shared across instances: global dispatch order
        self.lock = threading.Lock()
        self.stage_delay = stage_delay
        self.fail_at = fail_at
        self.finalized = []

    def interval_dispatches(self, task, devices, tid,
                            override_batch_count=None, shared=False):
        n = int(override_batch_count or 1)
        for u in range(n):
            if self.fail_at is not None and u == self.fail_at:
                raise RuntimeError(f"injected dispatch failure at unit {u}")
            if shared and self.stage_delay:
                ready = time.monotonic() + self.stage_delay
                while time.monotonic() < ready:
                    yield ("waiting", u)
            with self.lock:
                self.log.append((task.name, u))
            yield ("dispatched", u)
        yield ("drain", n)
        with self.lock:
            self.finalized.append(task.name)

    def execute(self, task, devices, tid, override_batch_count=None):
        for _ in self.interval_dispatches(
            task, devices, tid, override_batch_count=override_batch_count
        ):
            pass

    def search(self, task, devices, tid):
        return {}, 0.001


def co_plan(names, block=None, co=None, deps=None, starts=None):
    block = block if block is not None else Block(0, 4)
    return Plan(
        assignments={
            n: Assignment(
                block.size, block,
                float(starts[n]) if starts else 0.0, 1.0,
            )
            for n in names
        },
        makespan=1.0,
        dependencies=deps if deps is not None else {n: [] for n in names},
        coschedule=co or [],
    )


# ----------------------------------------------------------------- solver
class TestCoscheduleCandidates:
    def _choices(self, rt1=10.0, rt2=8.0):
        return {
            "a": [(4, Block(0, 4), rt1)],
            "b": [(4, Block(0, 4), rt2)],
        }

    def test_host_fraction_predicts_win(self):
        tech = RecordingTech()
        a = FakeTask("a", 10, [4], tech, hf=0.8)
        b = FakeTask("b", 10, [4], tech, hf=0.0)
        cands = coschedule_candidates([a, b], self._choices(), 1.15)
        assert len(cands) == 1
        n1, n2, common = cands[0]
        assert {n1, n2} == {"a", "b"}
        # comb = max(10, 8, 0.2*10 + 1.0*8) = 10 -> gain 18/10 = 1.8
        assert common[0][2] == pytest.approx(10.0)

    def test_zero_host_fraction_never_qualifies(self):
        """Two compute-bound jobs: comb = rt1 + rt2, gain exactly 1.0x."""
        tech = RecordingTech()
        a = FakeTask("a", 10, [4], tech, hf=0.0)
        b = FakeTask("b", 10, [4], tech, hf=0.0)
        assert coschedule_candidates([a, b], self._choices(), 1.15) == []

    def test_min_gain_threshold(self):
        tech = RecordingTech()
        a = FakeTask("a", 10, [4], tech, hf=0.8)
        b = FakeTask("b", 10, [4], tech, hf=0.0)
        assert coschedule_candidates([a, b], self._choices(), 2.0) == []

    def test_bubble_fraction_qualifies_pair(self):
        """Round 20: a schedule bubble is a device-idle window exactly like a
        host stall — a GPipe-shaped task with zero host fraction still
        qualifies for co-location on its bubble alone."""
        tech = RecordingTech()
        a = FakeTask("a", 10, [4], tech, hf=0.0)
        b = FakeTask("b", 10, [4], tech, hf=0.0)
        a.strategies[4].bubble_fraction = 0.8  # deep-pipeline GPipe bubble
        cands = coschedule_candidates([a, b], self._choices(), 1.15)
        assert len(cands) == 1
        # comb = max(10, 8, 0.2*10 + 8) = 10 -> same win as hf=0.8
        assert cands[0][2][0][2] == pytest.approx(10.0)

    def test_smaller_1f1b_bubble_shrinks_the_gain(self):
        """1F1B's smaller bubble is priced honestly: less idle to fill means
        less co-location gain than the same pair under GPipe's bubble."""
        from saturn_tpu.ops.pipeline import schedule_bubble_fraction

        tech = RecordingTech()
        gp = schedule_bubble_fraction("gpipe", 4, 4)   # 3/7
        f1 = schedule_bubble_fraction("1f1b", 4, 4)    # 3/10

        def comb_for(bubble):
            a = FakeTask("a", 10, [4], tech, hf=0.0)
            b = FakeTask("b", 10, [4], tech, hf=0.0)
            a.strategies[4].bubble_fraction = bubble
            b.strategies[4].bubble_fraction = bubble
            cands = coschedule_candidates([a, b], self._choices(), 1.0001)
            assert cands, f"bubble {bubble} should still qualify"
            return cands[0][2][0][2]

        assert comb_for(f1) > comb_for(gp)  # less fillable idle -> worse comb

    def test_bubble_and_host_fraction_compose(self):
        """The fillable window is host + bubble (clamped): together they can
        absorb a partner neither could alone."""
        tech = RecordingTech()
        a = FakeTask("a", 10, [4], tech, hf=0.5)
        b = FakeTask("b", 10, [4], tech, hf=0.0)
        a.strategies[4].bubble_fraction = 0.5
        cands = coschedule_candidates([a, b], self._choices(), 1.15)
        assert len(cands) == 1
        # fillable = min(1, 0.5 + 0.5) = 1.0 -> comb = max(10, 8, 0*10 + 8)
        assert cands[0][2][0][2] == pytest.approx(10.0)

    def test_disjoint_options_never_pair(self):
        tech = RecordingTech()
        a = FakeTask("a", 10, [4], tech, hf=0.9)
        b = FakeTask("b", 10, [4], tech, hf=0.9)
        choices = {
            "a": [(4, Block(0, 4), 10.0)],
            "b": [(4, Block(4, 4), 8.0)],  # different block: no common option
        }
        assert coschedule_candidates([a, b], choices, 1.15) == []


class TestSolverCoLocation:
    def test_contended_pair_coscheduled(self):
        """Two whole-topology jobs, one stage-bound: the MILP co-locates them
        and the makespan collapses to ~max(rt) instead of the serial sum."""
        tech = RecordingTech()
        hosty = FakeTask("hosty", 100, [4], tech, pbt=0.1, hf=0.8)
        compy = FakeTask("compy", 100, [4], tech, pbt=0.08, hf=0.0)
        plan = solve([hosty, compy], topo(4))
        assert plan.coschedule and sorted(plan.coschedule[0]) == [
            "compy", "hosty"
        ]
        a1, a2 = plan.assignments["hosty"], plan.assignments["compy"]
        assert a1.block.overlaps(a2.block)
        # interleaved occupancy ~ max(10, 8, 0.2*10 + 8) = 10, not 18
        assert plan.makespan <= 10.0 + 1e-6
        # groupmates carry no ordering edge between them
        assert "compy" not in plan.dependencies.get("hosty", [])
        assert "hosty" not in plan.dependencies.get("compy", [])

    def test_roomy_topology_prefers_disjoint(self):
        """With room to run side by side, co-location must not be chosen:
        disjoint placement gives the same makespan without sharing chips."""
        tech = RecordingTech()
        hosty = FakeTask("hosty", 100, [4], tech, pbt=0.1, hf=0.8)
        compy = FakeTask("compy", 100, [4], tech, pbt=0.08, hf=0.0)
        plan = solve([hosty, compy], topo(8))
        assert plan.coschedule == []
        a1, a2 = plan.assignments["hosty"], plan.assignments["compy"]
        assert not a1.block.overlaps(a2.block)

    def test_unmeasured_host_fraction_stays_serial(self):
        """hf defaults to 0.0 (pre-existing cache entries): the pair predicts
        no win, so contention serializes exactly as before this round."""
        tech = RecordingTech()
        t1 = FakeTask("a", 100, [4], tech, pbt=0.1, hf=0.0)
        t2 = FakeTask("b", 100, [4], tech, pbt=0.08, hf=0.0)
        plan = solve([t1, t2], topo(4))
        assert plan.coschedule == []
        assert plan.makespan >= 18.0 - 1e-6  # serial sum, plus slack

    def test_plan_json_roundtrip_keeps_groups(self):
        plan = co_plan(["a", "b"], co=[["a", "b"]])
        back = Plan.from_json(plan.to_json())
        assert back.coschedule == [["a", "b"]]

    def test_compute_dependencies_skips_groupmates(self):
        plan = co_plan(["a", "b"], co=[["a", "b"]])
        plan.compute_dependencies()
        assert plan.dependencies["a"] == [] and plan.dependencies["b"] == []
        # without the group, the same overlap produces an ordering edge
        solo = co_plan(["a", "b"])
        solo.compute_dependencies()
        assert solo.dependencies["a"] or solo.dependencies["b"]


# ------------------------------------------------------------- race guard
class TestRaceGuardCondensation:
    """engine._check_disjoint on the condensed (group-level) graph: the
    co-schedule edge composes with transitive serialization."""

    def test_copair_overlap_allowed(self):
        tech = RecordingTech()
        t1, t2 = FakeTask("a", 4, [4], tech), FakeTask("b", 4, [4], tech)
        plan = co_plan(["a", "b"], co=[["a", "b"]])
        engine.execute([t1, t2], {"a": 4, "b": 4}, 10.0, plan, topo(8))
        assert len(tech.calls) == 2

    def test_overlap_without_edge_still_races(self):
        tech = RecordingTech()
        t1, t2 = FakeTask("a", 4, [4], tech), FakeTask("b", 4, [4], tech)
        # a coschedule group naming only non-running tasks must not license
        # the overlap
        plan = co_plan(["a", "b"], co=[["x", "y"]])
        with pytest.raises(RuntimeError, match="races"):
            engine.execute([t1, t2], {"a": 4, "b": 4}, 10.0, plan, topo(8))
        assert not tech.calls

    def test_copair_inside_chain_serializes_transitively(self):
        """c depends on group member b and overlaps the group's block: the
        condensed graph serializes (group, c) — no race, ordered launch."""
        tech = RecordingTech(per_batch=0.005)
        tasks = [FakeTask(n, 4, [4], tech) for n in ("a", "b", "c")]
        plan = co_plan(
            ["a", "b", "c"], co=[["a", "b"]],
            deps={"a": [], "b": [], "c": ["b"]},
            starts={"a": 0.0, "b": 0.0, "c": 1.0},
        )
        engine.execute(tasks, {n: 4 for n in "abc"}, 10.0, plan, topo(8))
        assert len(tech.calls) == 3
        order = [c[0] for c in sorted(tech.calls, key=lambda c: c[3])]
        assert order.index("c") > max(order.index("a"), order.index("b"))

    def test_cycle_through_group_refused(self):
        """a,b are one condensed node; a->c and c->b is a group-level cycle
        — refused loudly, nothing launches."""
        tech = RecordingTech()
        tasks = [FakeTask(n, 4, [4], tech) for n in ("a", "b", "c")]
        plan = co_plan(
            ["a", "b", "c"], co=[["a", "b"]],
            deps={"a": ["c"], "b": [], "c": ["b"]},
        )
        with pytest.raises(RuntimeError, match="cycle"):
            engine.execute(tasks, {n: 4 for n in "abc"}, 10.0, plan, topo(8))
        assert not tech.calls

    def test_intra_group_dependency_refused(self):
        """A member waiting on its groupmate's completion event would
        deadlock the shared launcher — refused before launch."""
        tech = RecordingTech()
        t1, t2 = FakeTask("a", 4, [4], tech), FakeTask("b", 4, [4], tech)
        plan = co_plan(["a", "b"], co=[["a", "b"]],
                       deps={"a": [], "b": ["a"]})
        with pytest.raises(RuntimeError, match="groupmate"):
            engine.execute([t1, t2], {"a": 4, "b": 4}, 10.0, plan, topo(8))
        assert not tech.calls

    def test_plain_chain_still_allowed(self):
        """Pre-round-11 behavior intact: a->b->c serializes (a, c)."""
        tech = RecordingTech()
        tasks = [FakeTask(n, 4, [4], tech) for n in ("a", "b", "c")]
        plan = co_plan(
            ["a", "b", "c"],
            deps={"a": [], "b": ["a"], "c": ["b"]},
            starts={"a": 0.0, "b": 1.0, "c": 2.0},
        )
        engine.execute(tasks, {n: 4 for n in "abc"}, 10.0, plan, topo(8))
        assert len(tech.calls) == 3


# --------------------------------------------------------- group launcher
class TestGroupLauncher:
    def test_stage_bound_member_is_filled_by_neighbor(self):
        """'hosty' stages (yields "waiting") before every dispatch; 'compy'
        dispatches instantly. The launcher must run compy's units during
        hosty's staging gaps instead of parking — compy finishes all its
        dispatches before hosty does."""
        log = []
        hosty_tech = GenTech(log, stage_delay=0.01)
        compy_tech = GenTech(log)
        h = FakeTask("hosty", 4, [4], hosty_tech)
        c = FakeTask("compy", 4, [4], compy_tech)
        plan = co_plan(["hosty", "compy"], co=[["hosty", "compy"]])
        done = []
        engine.execute(
            [h, c], {"hosty": 4, "compy": 4}, 10.0, plan, topo(8),
            on_task_done=lambda name, n: done.append((name, n)),
        )
        assert len(log) == 8
        h_positions = [i for i, (n, _) in enumerate(log) if n == "hosty"]
        c_positions = [i for i, (n, _) in enumerate(log) if n == "compy"]
        # compy's device work filled hosty's host phases: every compy unit
        # dispatched before hosty's last unit
        assert max(c_positions) < max(h_positions)
        # per-member dispatch ORDER is the solo order regardless of packing
        assert [u for n, u in log if n == "hosty"] == [0, 1, 2, 3]
        assert [u for n, u in log if n == "compy"] == [0, 1, 2, 3]
        # drains resumed: both members ran their blocking finalization
        assert hosty_tech.finalized == ["hosty"]
        assert compy_tech.finalized == ["compy"]
        # bookkeeping fired per member: cursor advance, durability callback,
        # attributed realized feedback
        assert h.current_batch == 4 and c.current_batch == 4
        assert sorted(done) == [("compy", 4), ("hosty", 4)]
        assert len(h.realized) == 1 and len(c.realized) == 1
        assert h.realized[0] > 0 and c.realized[0] > 0

    def test_sequential_fallback_for_plain_technique(self):
        """A group member whose technique lacks generator support still runs
        (sequentially, after the interleaved members) — correctness never
        depends on supports_coschedule."""
        log = []
        gen_tech = GenTech(log)
        plain_tech = RecordingTech()
        g = FakeTask("gen", 3, [4], gen_tech)
        p = FakeTask("plain", 3, [4], plain_tech)
        plan = co_plan(["gen", "plain"], co=[["gen", "plain"]])
        engine.execute([g, p], {"gen": 3, "plain": 3}, 10.0, plan, topo(8))
        assert [u for n, u in log if n == "gen"] == [0, 1, 2]
        assert len(plain_tech.calls) == 1
        assert g.current_batch == 3 and p.current_batch == 3

    def test_member_failure_isolates(self):
        """One member's dispatch failure surfaces in errors; the healthy
        groupmate still completes its interval."""
        log = []
        bad_tech = GenTech(log, fail_at=1)
        good_tech = GenTech(log)
        bad = FakeTask("bad", 4, [4], bad_tech)
        good = FakeTask("good", 4, [4], good_tech)
        plan = co_plan(["bad", "good"], co=[["bad", "good"]])
        errors = engine.execute(
            [bad, good], {"bad": 4, "good": 4}, 10.0, plan, topo(8),
            failure_policy="drop",
        )
        assert set(errors) == {"bad"}
        assert good.current_batch == 4
        assert good_tech.finalized == ["good"]
        assert bad.current_batch == 0  # failed member advanced nothing

    def test_dependent_waits_for_whole_group(self):
        """A task depending on one group member must observe the WHOLE group
        finished (members share the block until the last drains)."""
        log = []
        slow = GenTech(log, stage_delay=0.01)
        fast = GenTech(log)
        after = RecordingTech()
        a = FakeTask("a", 3, [4], slow)
        b = FakeTask("b", 3, [4], fast)
        c = FakeTask("c", 3, [4], after)
        plan = co_plan(
            ["a", "b", "c"], co=[["a", "b"]],
            deps={"a": [], "b": [], "c": ["b"]},
            starts={"a": 0.0, "b": 0.0, "c": 1.0},
        )
        starts = []
        engine.execute(
            [a, b, c], {n: 3 for n in "abc"}, 10.0, plan, topo(8),
            on_task_start=lambda name: starts.append(
                (name, list(slow.finalized), list(fast.finalized))
            ),
        )
        assert len(log) == 6
        # when c launched, BOTH members had already drained and finalized:
        # the group's completion events fire only at group end
        c_entry = next(s for s in starts if s[0] == "c")
        assert c_entry[1] == ["a"] and c_entry[2] == ["b"]


# ---------------------------------------------------------- window policy
class WindowedTech(BaseTechnique):
    name = "windowed"
    supports_windows = True

    def __init__(self):
        self.windows = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None,
                window_size=None):
        with self.lock:
            self.windows.append((task.name, window_size))

    def search(self, task, devices, tid):
        return {}, 0.001


class TestWindowCapPerInterval:
    def test_pick_window_honors_explicit_cap(self, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "8")
        assert engine.pick_window(100, cap=2) == 2
        assert engine.pick_window(100) == 8  # None still reads the env

    def test_cap_resolved_once_per_interval(self, monkeypatch):
        calls = {"n": 0}
        real = engine._window_cap

        def counting():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(engine, "_window_cap", counting)
        tech = WindowedTech()
        t1 = FakeTask("a", 8, [4], tech)
        t2 = FakeTask("b", 8, [4], tech)
        plan = Plan(
            assignments={
                "a": Assignment(4, Block(0, 4), 0.0, 1.0),
                "b": Assignment(4, Block(4, 4), 0.0, 1.0),
            },
            makespan=1.0,
            dependencies={"a": [], "b": []},
        )
        engine.execute([t1, t2], {"a": 8, "b": 8}, 10.0, plan, topo(8))
        assert calls["n"] == 1

    def test_env_flip_mid_interval_cannot_split_policy(self, monkeypatch):
        """The cap is frozen at interval start: a SATURN_TPU_MAX_WINDOW flip
        while task 'a' runs must not change task 'b''s window."""
        import os

        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "3")

        class FlippingTech(WindowedTech):
            def execute(self, task, devices, tid, override_batch_count=None,
                        window_size=None):
                super().execute(task, devices, tid,
                                override_batch_count=override_batch_count,
                                window_size=window_size)
                os.environ["SATURN_TPU_MAX_WINDOW"] = "1"

        tech = FlippingTech()
        t1 = FakeTask("a", 8, [4], tech)
        t2 = FakeTask("b", 8, [4], tech)
        plan = co_plan(
            ["a", "b"], deps={"a": [], "b": ["a"]},
            starts={"a": 0.0, "b": 1.0},
        )
        engine.execute([t1, t2], {"a": 8, "b": 8}, 10.0, plan, topo(8))
        assert dict(tech.windows) == {"a": 3, "b": 3}


# ------------------------------------------------------------- prefetcher
class TestTryNext:
    def test_not_ready_then_value(self):
        from saturn_tpu.data.prefetch import NOT_READY, DevicePrefetcher

        gate = threading.Event()

        def stage(i):
            gate.wait(2.0)
            return i * 10

        pf = DevicePrefetcher(2, stage, depth=2)
        try:
            assert pf.try_next() is NOT_READY  # staging parked on the gate
            gate.set()
            deadline = time.monotonic() + 2.0
            got = pf.try_next()
            while got is NOT_READY and time.monotonic() < deadline:
                time.sleep(0.001)
                got = pf.try_next()
            assert got == 0
        finally:
            pf.close()

    def test_exhaustion_raises_stopiteration(self):
        from saturn_tpu.data.prefetch import NOT_READY, DevicePrefetcher

        pf = DevicePrefetcher(2, lambda i: i, depth=2)
        try:
            seen = []
            while len(seen) < 2:
                got = pf.try_next()
                if got is not NOT_READY:
                    seen.append(got)
            assert seen == [0, 1]
            with pytest.raises(StopIteration):
                pf.try_next()
        finally:
            pf.close()

    def test_stage_error_reraised(self):
        from saturn_tpu.data.prefetch import NOT_READY, DevicePrefetcher

        def stage(i):
            raise ValueError("boom")

        pf = DevicePrefetcher(3, stage, depth=2)
        try:
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                try:
                    got = pf.try_next()
                except ValueError:
                    break
                assert got is NOT_READY
                time.sleep(0.001)
            else:
                pytest.fail("staged error never surfaced")
        finally:
            pf.close()


# -------------------------------------------------------------- AOT cache
class TestAotCache:
    @pytest.fixture(autouse=True)
    def _isolated(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_AOT_CACHE", "1")
        monkeypatch.setenv("SATURN_TPU_PROFILE_CACHE_DIR", str(tmp_path))
        yield

    def _lowered(self):
        import jax

        return jax.jit(lambda x: x * 2.0 + 1.0).lower(np.arange(8.0))

    def test_miss_store_hit_roundtrip(self):
        import os

        from saturn_tpu.utils import aot_cache

        x = np.arange(8.0)
        s0 = aot_cache.stats()
        c1 = self._lowered().compile()
        got1 = aot_cache.load_or_compile(self._lowered())
        s1 = aot_cache.stats()
        assert s1["misses"] - s0["misses"] == 1
        assert s1["stores"] - s0["stores"] == 1
        assert os.listdir(aot_cache.cache_dir())
        got2 = aot_cache.load_or_compile(self._lowered())
        s2 = aot_cache.stats()
        assert s2["hits"] - s1["hits"] == 1
        np.testing.assert_array_equal(np.asarray(got2(x)), np.asarray(c1(x)))
        np.testing.assert_array_equal(np.asarray(got1(x)), np.asarray(c1(x)))

    def test_corrupt_entry_degrades_to_recompile(self):
        import os

        from saturn_tpu.utils import aot_cache

        aot_cache.load_or_compile(self._lowered())
        (entry,) = [
            os.path.join(aot_cache.cache_dir(), f)
            for f in os.listdir(aot_cache.cache_dir())
        ]
        with open(entry, "wb") as f:
            f.write(b"not a pickle")
        s0 = aot_cache.stats()
        got = aot_cache.load_or_compile(self._lowered())
        s1 = aot_cache.stats()
        assert s1["errors"] - s0["errors"] == 1
        assert s1["misses"] - s0["misses"] == 1  # corrupt entry = a miss
        np.testing.assert_array_equal(
            np.asarray(got(np.arange(8.0))), np.arange(8.0) * 2.0 + 1.0
        )

    def test_device_block_is_part_of_the_key(self):
        """Twin programs pinned to different blocks must never collide: the
        physical device assignment lives only in the executable."""
        import jax

        from saturn_tpu.utils import aot_cache

        low = self._lowered()
        devs = jax.devices()
        k1 = aot_cache.cache_key(low, devs[:4])
        k2 = aot_cache.cache_key(low, devs[4:])
        assert k1 and k2 and k1 != k2
        assert aot_cache.cache_key(low, devs[:4]) == k1  # stable

    def test_cpu_default_off_without_optin(self, monkeypatch):
        from saturn_tpu.utils import aot_cache

        monkeypatch.delenv("SATURN_TPU_AOT_CACHE", raising=False)
        # conftest pins JAX_PLATFORMS=cpu, so the unset default must be OFF
        # (the poisoned-cache hazard documented in tests/conftest.py)
        assert not aot_cache.enabled()
        monkeypatch.setenv("SATURN_TPU_AOT_CACHE", "0")
        assert not aot_cache.enabled()


# ------------------------------------------------- host-fraction plumbing
class HFTech(BaseTechnique):
    """Feasible everywhere; reports a fixed measured host fraction."""

    name = "hf"
    calls: list = []

    def search(self, task, devices, tid):
        type(self).calls.append((task.name, len(devices)))
        g = len(devices)
        self._hf = getattr(self, "_hf", {})
        self._hf[(task.name, g)] = 0.7
        return {"knob": g}, 0.08 / g + 0.02

    def host_fraction_report(self, task_name, size):
        return getattr(self, "_hf", {}).pop((task_name, size), None)

    def execute(self, task, devices, tid, override_batch_count=None):
        pass


class EvalTask:
    """Evaluator-facing duck type (mirrors tests/test_profile_cache.py)."""

    class _DS:
        batch_size = 8

        def __len__(self):
            return 8

        def example_batch(self):
            return np.zeros((8, 64), dtype=np.int32)

        def batch(self, i):
            return self.example_batch()

    class _HP:
        optimizer = "adamw"
        kwargs: dict = {}

    def __init__(self, name):
        self.name = name
        self.chip_range = None
        self.total_batches = 100
        self.strategies = {}
        self.hints = {}
        self.hparams = self._HP()

    def get_model(self, **kw):
        return ("cfg-v1",)

    def get_dataset(self):
        return self._DS()

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}


class TestHostFractionPlumbing:
    @pytest.fixture(autouse=True)
    def _registry(self):
        from saturn_tpu import library

        library.register("hf", HFTech)
        HFTech.calls = []
        yield
        library.deregister("hf")

    def test_sweep_installs_and_cache_preserves(self, tmp_path):
        from saturn_tpu.trial_runner import evaluator

        cache_dir = str(tmp_path / "cache")
        t = EvalTask("hfjob")
        evaluator.search([t], technique_names=["hf"], topology=topo(8),
                         profile_cache=cache_dir, prune=False)
        measured = {g: s for g, s in t.strategies.items() if s.feasible}
        assert measured
        assert all(s.host_fraction == pytest.approx(0.7)
                   for s in measured.values())
        # a second sweep over the same signature is trial-free AND keeps the
        # measured host fraction through the persistent cache
        HFTech.calls = []
        t2 = EvalTask("hfjob")
        evaluator.search([t2], technique_names=["hf"], topology=topo(8),
                         profile_cache=cache_dir, prune=False)
        assert HFTech.calls == []
        m2 = {g: s for g, s in t2.strategies.items() if s.feasible}
        assert m2
        assert all(s.host_fraction == pytest.approx(0.7)
                   for s in m2.values())


# ------------------------------------------- real-program trajectory proof
def _real_task(tmp_path, tag, name):
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 8,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=6),
        chip_range=[4],
        name=name,  # the init PRNG stream follows the name
        save_dir=str(tmp_path / tag),
    )


def _with_strategy(task, tech, size=4):
    task.strategies = {
        size: Strategy(executor=tech, apportionment=size, params={},
                       runtime=1.0, per_batch_time=0.1)
    }
    return task


@pytest.mark.perf
class TestTrajectoryEquivalence:
    def test_interleaved_pair_matches_solo_bitwise(self, tmp_path, devices8):
        """Acceptance: run job A solo, then a fresh job A interleaved with a
        co-located neighbor B on the SAME block via the group launcher. A's
        final checkpoint (params, optimizer state, step) must be
        bit-identical — interleaving changes wall-clock packing only."""
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.utils import checkpoint as ckpt

        real_topo = SliceTopology(devices8)

        solo = _with_strategy(
            _real_task(tmp_path, "solo", "co-eq"), DataParallel()
        )
        plan_solo = Plan(
            assignments={"co-eq": Assignment(4, Block(0, 4), 0.0, 1.0)},
            makespan=1.0, dependencies={"co-eq": []},
        )
        engine.execute([solo], {"co-eq": 6}, 100.0, plan_solo, real_topo)
        ckpt.flush()
        ref = ckpt.load_arrays(solo.ckpt_path)

        pair_a = _with_strategy(
            _real_task(tmp_path, "pair-a", "co-eq"), DataParallel()
        )
        pair_b = _with_strategy(
            _real_task(tmp_path, "pair-b", "co-mate"), DataParallel()
        )
        plan_co = Plan(
            assignments={
                "co-eq": Assignment(4, Block(0, 4), 0.0, 1.0),
                "co-mate": Assignment(4, Block(0, 4), 0.0, 1.0),
            },
            makespan=1.0,
            dependencies={"co-eq": [], "co-mate": []},
            coschedule=[["co-eq", "co-mate"]],
        )
        errors = engine.execute(
            [pair_a, pair_b], {"co-eq": 6, "co-mate": 6}, 100.0, plan_co,
            real_topo,
        )
        assert not errors
        ckpt.flush()
        got = ckpt.load_arrays(pair_a.ckpt_path)

        assert int(ref["step"]) == int(got["step"]) == 6
        assert set(ref) == set(got)
        for key in ref:
            np.testing.assert_array_equal(ref[key], got[key], err_msg=key)
        # the neighbor also completed its own 6 steps
        mate = ckpt.load_arrays(pair_b.ckpt_path)
        assert int(mate["step"]) == 6


# ------------------------------------------------- preemption accounting
class PreemptOnceTech(GenTech):
    """GenTech whose injected failure surfaces as a slice preemption, once:
    the first dispatch of ``victim`` raises ``PreemptedError``; every later
    attempt runs clean (the task resumed on surviving chips)."""

    def __init__(self, log, victim):
        super().__init__(log)
        self.victim = victim
        self.fired = False

    def interval_dispatches(self, task, devices, tid,
                            override_batch_count=None, shared=False):
        if task.name == self.victim and not self.fired:
            self.fired = True
            raise PreemptedError(f"slice under {task.name} preempted")
            yield  # pragma: no cover - marks this as a generator
        yield from super().interval_dispatches(
            task, devices, tid,
            override_batch_count=override_batch_count, shared=shared,
        )


class TestPreemptedGroupMemberAccounting:
    """PR-8 satellite: a preemption inside a co-schedule group must stay the
    preempted member's event — the surviving partner keeps its interval, and
    neither job is charged a retry (losing chips is the fleet's fault)."""

    def test_partner_survives_member_preemption(self):
        log = []
        tech = PreemptOnceTech(log, victim="bad")
        bad = FakeTask("bad", 4, [4], tech)
        good = FakeTask("good", 4, [4], tech)
        plan = co_plan(["bad", "good"], co=[["bad", "good"]])
        errors = engine.execute(
            [bad, good], {"bad": 4, "good": 4}, 10.0, plan, topo(8),
            failure_policy="drop",
        )
        # the typed error reaches the orchestrator intact — that type is
        # what routes it to the no-retry-charge requeue path
        assert set(errors) == {"bad"}
        assert isinstance(errors["bad"], PreemptedError)
        assert good.current_batch == 4
        assert tech.finalized == ["good"]
        assert bad.current_batch == 0  # nothing realized on the lost member

    def test_preemption_charges_no_retry_budget(self):
        """End to end with a ZERO retry budget: the contended pair
        co-schedules, one member is preempted mid-group, and both jobs still
        complete — a preemption charged to ``max_task_retries`` would have
        failed the victim outright."""
        from saturn_tpu.executor.orchestrator import orchestrate

        log = []
        tech = PreemptOnceTech(log, victim="hosty")
        hosty = FakeTask("hosty", 12, [4], tech, pbt=0.005, hf=0.8)
        compy = FakeTask("compy", 12, [4], tech, pbt=0.004, hf=0.0)
        for t in (hosty, compy):
            t.hints = {}
            t.chip_range = None
        out = orchestrate(
            [hosty, compy], interval=0.5, topology=topo(4),
            failure_policy="retry", max_task_retries=0,
        )
        assert sorted(out["completed"]) == ["compy", "hosty"]
        assert out["failed"] == {}
        assert tech.fired
        # the partner's batches ran exactly once — its interval was neither
        # aborted nor rolled back by the groupmate's preemption
        assert len([u for n, u in log if n == "compy"]) == 12
        assert len([u for n, u in log if n == "hosty"]) == 12
