"""saturn-lint regression tests: one test per diagnostic code, gate
placement (service quarantine crash marker), CLI, and cache fingerprint
coupling. The differential static/dynamic oracle lives in
``test_analysis_differential.py``."""

import json
import os
from types import SimpleNamespace

import pytest

from saturn_tpu import analysis
from saturn_tpu.analysis import jax_lint, plan_verifier
from saturn_tpu.analysis.diagnostics import PlanVerificationError
from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.solver.milp import Assignment, Plan

pytestmark = pytest.mark.analysis


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


def mk_plan(assignments, deps=None, coschedule=None, makespan=None):
    ends = [a.start + a.runtime for a in assignments.values()] or [0.0]
    plan = Plan(
        assignments=assignments,
        makespan=max(ends) if makespan is None else makespan,
        dependencies=deps if deps is not None else {},
        coschedule=coschedule or [],
    )
    if deps is None:
        plan.compute_dependencies()
    return plan


def codes_of(report):
    return set(report.codes())


# --------------------------------------------------------------------- pass 1
class TestLaunchDiagnostics:
    def test_race_code_and_message(self):
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 0.0, 1.0),
            "b": Assignment(4, Block(0, 4), 0.0, 1.0),
        }, deps={"a": [], "b": []})
        report = analysis.verify_plan(plan)
        assert "SAT-P001" in codes_of(report) and not report.ok
        with pytest.raises(RuntimeError, match="races"):
            plan_verifier.check_launch_invariants(["a", "b"], plan)

    def test_cycle_code_and_message(self):
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 0.0, 1.0),
            "b": Assignment(4, Block(4, 4), 0.0, 1.0),
        }, deps={"a": ["b"], "b": ["a"]})
        report = analysis.verify_plan(plan)
        assert "SAT-P002" in codes_of(report)
        with pytest.raises(RuntimeError, match="cycle"):
            plan_verifier.check_launch_invariants(["a", "b"], plan)

    def test_groupmate_code_and_message(self):
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 0.0, 1.0),
            "b": Assignment(4, Block(0, 4), 0.0, 1.0),
        }, deps={"a": [], "b": ["a"]}, coschedule=[["a", "b"]])
        report = analysis.verify_plan(plan)
        assert "SAT-P003" in codes_of(report)
        with pytest.raises(RuntimeError, match="groupmate"):
            plan_verifier.check_launch_invariants(["a", "b"], plan)

    def test_transitive_serialization_accepted(self):
        plan = mk_plan({
            n: Assignment(4, Block(0, 4), float(i), 1.0)
            for i, n in enumerate("abc")
        }, deps={"a": [], "b": ["a"], "c": ["b"]})
        assert analysis.verify_plan(plan).ok

    def test_coschedule_overlap_accepted(self):
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 0.0, 1.0),
            "b": Assignment(4, Block(0, 4), 0.0, 1.0),
        }, deps={"a": [], "b": []}, coschedule=[["a", "b"]])
        assert analysis.verify_plan(plan).ok


class TestStructureDiagnostics:
    def test_unknown_dep_name(self):
        plan = mk_plan({"a": Assignment(4, Block(0, 4), 0.0, 1.0)},
                       deps={"a": ["ghost"]})
        report = analysis.verify_plan(plan)
        assert "SAT-P010" in codes_of(report) and report.ok  # warning only

    def test_unknown_coschedule_member_and_small_group(self):
        plan = mk_plan({"a": Assignment(4, Block(0, 4), 0.0, 1.0)},
                       deps={"a": []}, coschedule=[["a", "ghost"]])
        report = analysis.verify_plan(plan)
        assert {"SAT-P011", "SAT-P012"} <= codes_of(report) and report.ok

    def test_task_in_two_groups(self):
        plan = mk_plan({
            "a": Assignment(2, Block(0, 2), 0.0, 1.0),
            "b": Assignment(2, Block(0, 2), 0.0, 1.0),
            "c": Assignment(2, Block(0, 2), 0.0, 1.0),
        }, deps={}, coschedule=[["a", "b"], ["b", "c"]])
        report = analysis.verify_plan(plan)
        assert "SAT-P013" in codes_of(report)


class TestFeasibilityDiagnostics:
    def test_block_beyond_capacity(self):
        plan = mk_plan({"a": Assignment(8, Block(8, 8), 0.0, 1.0)}, deps={})
        report = analysis.verify_plan(plan, topology=topo(8))
        assert "SAT-P020" in codes_of(report) and not report.ok

    def test_apportionment_block_mismatch(self):
        plan = mk_plan({"a": Assignment(2, Block(0, 4), 0.0, 1.0)}, deps={})
        report = analysis.verify_plan(plan, topology=topo(8))
        assert "SAT-P021" in codes_of(report)

    def test_no_feasible_strategy(self):
        task = SimpleNamespace(
            name="a",
            strategies={4: SimpleNamespace(feasible=False, host_fraction=0.0)},
        )
        plan = mk_plan({"a": Assignment(4, Block(0, 4), 0.0, 1.0)}, deps={})
        report = analysis.verify_plan(plan, topology=topo(8), tasks=[task])
        assert "SAT-P022" in codes_of(report)

    def test_coschedule_group_block_mismatch_and_host_fraction(self):
        tasks = [
            SimpleNamespace(name=n, strategies={
                4: SimpleNamespace(feasible=True, host_fraction=0.0)
            })
            for n in ("a", "b")
        ]
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 0.0, 1.0),
            "b": Assignment(4, Block(4, 4), 0.0, 1.0),
        }, deps={}, coschedule=[["a", "b"]])
        report = analysis.verify_plan(plan, topology=topo(8), tasks=tasks)
        assert {"SAT-P023", "SAT-P024"} <= codes_of(report)
        assert report.ok  # advisory, not gate-blocking


class TestTimelineDiagnostics:
    def test_negative_start(self):
        plan = mk_plan({"a": Assignment(4, Block(0, 4), -1.0, 1.0)}, deps={})
        report = analysis.verify_plan(plan)
        assert "SAT-P030" in codes_of(report) and not report.ok

    def test_start_order_contradicts_dependency(self):
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 5.0, 1.0),
            "b": Assignment(4, Block(0, 4), 0.0, 1.0),
        }, deps={"a": [], "b": ["a"]})
        report = analysis.verify_plan(plan)
        assert "SAT-P031" in codes_of(report)

    def test_stale_makespan(self):
        plan = mk_plan({"a": Assignment(4, Block(0, 4), 0.0, 10.0)},
                       deps={}, makespan=1.0)
        report = analysis.verify_plan(plan)
        assert "SAT-P032" in codes_of(report) and report.ok

    def test_deadline_overrun(self):
        task = SimpleNamespace(
            name="a",
            strategies={4: SimpleNamespace(feasible=True, host_fraction=0.0)},
            deadline=5.0,
        )
        plan = mk_plan({"a": Assignment(4, Block(0, 4), 0.0, 10.0)}, deps={})
        report = analysis.verify_plan(plan, tasks=[task])
        assert "SAT-P033" in codes_of(report) and report.ok


class TestVerifyOrRaise:
    def test_raises_plan_verification_error(self):
        plan = mk_plan({
            "a": Assignment(4, Block(0, 4), 0.0, 1.0),
            "b": Assignment(4, Block(0, 4), 0.0, 1.0),
        }, deps={"a": [], "b": []})
        with pytest.raises(PlanVerificationError) as ei:
            analysis.verify_or_raise(plan, source="unit-test")
        assert isinstance(ei.value, RuntimeError)  # legacy callers unchanged
        assert "SAT-P001" in str(ei.value)
        assert ei.value.report.errors

    def test_clean_plan_returns_report(self):
        plan = mk_plan({"a": Assignment(4, Block(0, 4), 0.0, 1.0)}, deps={})
        report = analysis.verify_or_raise(plan, topology=topo(8))
        assert report.ok


# --------------------------------------------------------------------- pass 2
class TestRetraceRegistry:
    def test_novel_signature_flagged(self):
        reg = jax_lint.SignatureRegistry()
        sig_a = (("p", (8, 8), "float32"),)
        sig_b = (("p", (8, 16), "float32"),)
        assert reg.note("bundle", 4, sig_a) is None
        assert reg.note("bundle", 4, sig_a) is None  # same shapes: no risk
        diag = reg.note("bundle", 4, sig_b)
        assert diag is not None and diag.code == "SAT-L001"
        assert reg.note("bundle", 8, sig_b) is None  # different K: new key
        assert [d.code for d in reg.drain()] == ["SAT-L001"]


def _hot_loop_with_sync(xs):
    total = 0.0
    for x in xs:
        x.block_until_ready()
        total += float(x)
    return total


def _hot_loop_sanctioned(xs):
    total = 0.0
    for x in xs:
        x.block_until_ready()  # lint: sanctioned-host-sync
        total += 1
    return total


def _drain_after_loop(xs):
    last = None
    for x in xs:
        last = x
    return float(last)


class TestHostSyncLint:
    def test_sync_in_loop_flagged_with_location(self):
        diags = jax_lint.lint_host_syncs(_hot_loop_with_sync)
        assert {d.code for d in diags} == {"SAT-L002"}
        assert len(diags) == 2  # block_until_ready + float
        assert all(d.location and __file__.rstrip("c") in d.location
                   for d in diags)

    def test_sanction_marker_respected(self):
        assert jax_lint.lint_host_syncs(_hot_loop_sanctioned) == []

    def test_drain_after_loop_clean(self):
        assert jax_lint.lint_host_syncs(_drain_after_loop) == []

    def test_interval_hot_loop_is_clean(self):
        """The real dispatch hot loop carries exactly one sanctioned sync
        (the warmup fence) and nothing unsanctioned."""
        from saturn_tpu.parallel.spmd_base import SPMDTechnique

        assert jax_lint.lint_host_syncs(SPMDTechnique.interval_dispatches) == []


def _donation_bug(fused_fn, state, window):
    state, loss = fused_fn(state, window)
    return loss, window.sum()  # reads the donated window stack


def _donation_ok(fused_fn, stage, state, n):
    loss = None
    for i in range(n):
        window = stage(i)
        state, loss = fused_fn(state, window)
    return state, loss


class TestDonationLint:
    def test_donated_read_flagged(self):
        diags = jax_lint.lint_donation(_donation_bug,
                                       {"fused_fn": (0, 1)})
        assert [d.code for d in diags] == ["SAT-L003"]
        assert diags[0].counterexample["name"] == "window"
        assert diags[0].location

    def test_restaged_window_clean(self):
        assert jax_lint.lint_donation(_donation_ok,
                                      {"fused_fn": (0, 1)}) == []

    def test_interval_hot_loop_donation_clean(self):
        from saturn_tpu.parallel.spmd_base import SPMDTechnique

        assert jax_lint.lint_donation(
            SPMDTechnique.interval_dispatches,
            {"fused_fn": (0, 1), "single_fn": (0, 1)},
        ) == []


# Deliberately-broken rule functions for the seeded sharding-lint tests.
# Their def lines anchor the file:line assertions below.
def _bad_axis_rules(path, shape, mesh_axes):
    from jax.sharding import PartitionSpec as P

    return P("modell")  # typo'd axis name — not in any mesh


def _bad_divis_rules(path, shape, mesh_axes):
    from jax.sharding import PartitionSpec as P

    return P("data")  # shards dim 0 regardless of divisibility


class TestShardingLint:
    MESH_AXES = {"data": 4, "model": 2}

    def test_unknown_axis_file_line(self):
        report = jax_lint.lint_rules(
            _bad_axis_rules, {"w": (8, 8)}, self.MESH_AXES
        )
        assert [d.code for d in report.errors] == ["SAT-L010"]
        loc = report.errors[0].location
        assert loc and os.path.basename(__file__).rstrip("c") in loc
        # the line number points at the rule function's def
        assert int(loc.rsplit(":", 1)[1]) > 0

    def test_divisibility_violation_file_line(self):
        report = jax_lint.lint_rules(
            _bad_divis_rules, {"w": (6, 8)}, self.MESH_AXES
        )
        codes = [d.code for d in report.diagnostics]
        assert codes == ["SAT-L011"]
        assert report.diagnostics[0].severity == "warning"
        assert report.diagnostics[0].location
        strict = jax_lint.lint_rules(
            _bad_divis_rules, {"w": (6, 8)}, self.MESH_AXES, strict=True
        )
        assert not strict.ok  # strict mode promotes to error

    def test_rank_overflow(self):
        from jax.sharding import PartitionSpec as P

        diags = jax_lint.check_pspec(P("data", "model"), (8,),
                                     self.MESH_AXES)
        assert [d.code for d in diags] == ["SAT-L012"]

    def test_pspec_tree_gate_raises_on_bad_axis(self, devices8):
        """The pre-compile gate: a rule naming a nonexistent mesh axis is
        refused at pspec_tree time with the rule's file:line, on CPU."""
        import jax

        from saturn_tpu.core.mesh import make_submesh
        from saturn_tpu.parallel import sharding as shr

        mesh = make_submesh(devices8, ("data", "model"), (4, 2))
        shapes = {"w": jax.ShapeDtypeStruct((8, 8), "float32")}
        with pytest.raises(jax_lint.ShardingLintError) as ei:
            shr.pspec_tree(shapes, _bad_axis_rules, mesh)
        assert "SAT-L010" in str(ei.value)
        assert os.path.basename(__file__).rstrip("c") in str(ei.value)

    def test_pspec_tree_accepts_good_rules(self, devices8):
        import jax

        from saturn_tpu.core.mesh import make_submesh
        from saturn_tpu.parallel import sharding as shr

        mesh = make_submesh(devices8, ("data", "model"), (4, 2))
        shapes = {"w": jax.ShapeDtypeStruct((8, 8), "float32")}
        specs = shr.pspec_tree(shapes, shr.fsdp_rules(), mesh)
        assert specs["w"] is not None

    def test_builtin_fsdp_rules_lint_clean(self):
        from saturn_tpu.parallel import sharding as shr

        report = jax_lint.lint_rules(
            shr.fsdp_rules(),
            {"layer/kernel": (768, 3072), "layer/bias": (3072,)},
            {"data": 8},
        )
        assert report.ok and not report.diagnostics


# ------------------------------------------------------------------- journal
def _write_journal_with_plan(tmp_path, plan, name="wal"):
    from saturn_tpu.durability.journal import Journal

    root = str(tmp_path / name)
    j = Journal(root)
    j.append("plan_commit", interval=0, makespan=plan.makespan,
             plan=plan.to_json())
    j.commit()
    j.close()
    return root


def _racy_plan():
    return mk_plan({
        "a": Assignment(4, Block(0, 4), 0.0, 1.0),
        "b": Assignment(4, Block(0, 4), 0.0, 1.0),
    }, deps={"a": [], "b": []})


def _clean_plan():
    return mk_plan({
        "a": Assignment(4, Block(0, 4), 0.0, 1.0),
        "b": Assignment(4, Block(4, 4), 0.0, 1.0),
    }, deps={"a": [], "b": []})


class TestJournalAudit:
    def test_bad_plan_commit_flagged(self, tmp_path):
        root = _write_journal_with_plan(tmp_path, _racy_plan())
        report = analysis.audit_journal(root)
        codes = codes_of(report)
        assert {"SAT-J001", "SAT-P001"} <= codes and not report.ok

    def test_clean_journal_passes(self, tmp_path):
        root = _write_journal_with_plan(tmp_path, _clean_plan())
        report = analysis.audit_journal(root)
        assert report.ok and "SAT-J001" not in codes_of(report)

    def test_recovery_delegate(self, tmp_path):
        from saturn_tpu.durability import recovery as rmod

        root = _write_journal_with_plan(tmp_path, _racy_plan())
        assert not rmod.audit_plan_commits(root).ok


@pytest.mark.crash
class TestServiceQuarantine:
    """Satellite: journal recovery must QUARANTINE a replayed plan that
    fails static verification — fall back to a fresh solve, never adopt."""

    def test_recovered_racy_plan_quarantined(self, tmp_path):
        from saturn_tpu.durability import journal as jmod
        from saturn_tpu.service.server import SaturnService

        root = _write_journal_with_plan(tmp_path, _racy_plan())
        svc = SaturnService(topology=topo(8), durability_dir=root)
        try:
            assert svc._recovered_plan is None  # quarantined, not adopted
            kinds = [r["kind"] for r in jmod.replay(root)]
            assert "plan_quarantine" in kinds  # durable crash marker
        finally:
            svc.journal.close()

    def test_recovered_clean_plan_adopted(self, tmp_path):
        from saturn_tpu.durability import journal as jmod
        from saturn_tpu.service.server import SaturnService

        root = _write_journal_with_plan(tmp_path, _clean_plan())
        svc = SaturnService(topology=topo(8), durability_dir=root)
        try:
            assert svc._recovered_plan is not None
            kinds = [r["kind"] for r in jmod.replay(root)]
            assert "plan_quarantine" not in kinds
        finally:
            svc.journal.close()


# ----------------------------------------------------------------------- CLI
class TestCLI:
    def test_plan_subcommand(self, tmp_path, capsys):
        from saturn_tpu.analysis import cli

        path = str(tmp_path / "plan.json")
        with open(path, "w") as f:
            json.dump(_racy_plan().to_json(), f)
        assert cli.main(["plan", path]) == 1
        assert "SAT-P001" in capsys.readouterr().out
        with open(path, "w") as f:
            json.dump(_clean_plan().to_json(), f)
        assert cli.main(["--json", "plan", path, "--topology", "8"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] is True and out["schema"] == analysis.SCHEMA_VERSION

    def test_journal_subcommand(self, tmp_path, capsys):
        from saturn_tpu.analysis import cli

        root = _write_journal_with_plan(tmp_path, _racy_plan())
        assert cli.main(["journal", root]) == 1
        assert "SAT-J001" in capsys.readouterr().out

    def test_plan_subcommand_missing_file(self, tmp_path):
        from saturn_tpu.analysis import cli

        assert cli.main(["plan", str(tmp_path / "nope.json")]) == 2


# ---------------------------------------------------------------- fingerprint
class TestAnalysisSchemaInFingerprints:
    def test_profile_cache_fingerprint_tracks_analyzer_schema(self, monkeypatch):
        from saturn_tpu.utils import profile_cache as pcache

        before = pcache.fingerprint("t", "fsdp", 4, "topo", "per-step")
        monkeypatch.setattr("saturn_tpu.analysis.SCHEMA_VERSION",
                            analysis.SCHEMA_VERSION + 1)
        after = pcache.fingerprint("t", "fsdp", 4, "topo", "per-step")
        assert before != after

    def test_aot_runtime_identity_tracks_analyzer_schema(self):
        from saturn_tpu.utils import aot_cache

        ident = aot_cache._runtime_identity()
        assert f"lint{analysis.SCHEMA_VERSION}" in ident


class TestBenchGuardGate:
    def test_bench_plan_verifies(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_guard",
            os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                         "bench_guard.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.bench_plan_errors({"value": 1.0}) == []
