"""Async step pipeline (round 10): window policy, prefetcher, and the
fused-vs-per-step equivalence guarantees.

The tentpole's central claim is that the fused K-step dispatch is a pure
dispatch-shape change: ``lax.scan`` over the SAME train step the 1-step
program runs, so the loss trajectory and final checkpoint are bit-identical
for any K — including when a SimulatedKill lands mid-window (the interval
is all-or-nothing; the retry replays from the checkpoint).
"""

import numpy as np
import pytest

from saturn_tpu.core.strategy import Strategy
from saturn_tpu.data.prefetch import DevicePrefetcher
from saturn_tpu.parallel.spmd_base import (
    DEFAULT_MAX_WINDOW,
    choose_window,
    dispatch_signature,
    max_window,
)
from saturn_tpu.resilience.crash import SimulatedKill
from saturn_tpu.utils import checkpoint as ckpt


class TestWindowPolicy:
    def test_short_intervals_stay_per_step(self):
        assert choose_window(0) == 1
        assert choose_window(1) == 1

    def test_window_capped_by_budget_and_env(self, monkeypatch):
        monkeypatch.delenv("SATURN_TPU_MAX_WINDOW", raising=False)
        assert max_window() == DEFAULT_MAX_WINDOW
        assert choose_window(100) == DEFAULT_MAX_WINDOW
        assert choose_window(3) == 3  # budget below the cap wins
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "4")
        assert choose_window(100) == 4

    def test_cap_of_one_disables_fusion(self, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "1")
        assert choose_window(100) == 1

    def test_invalid_env_falls_back_to_default(self, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "banana")
        assert max_window() == DEFAULT_MAX_WINDOW
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "0")
        assert max_window() == 1  # clamped, never 0

    def test_dispatch_signature_tracks_window(self, monkeypatch):
        monkeypatch.delenv("SATURN_TPU_MAX_WINDOW", raising=False)
        assert dispatch_signature() == f"fused-scan-v1:k{DEFAULT_MAX_WINDOW}"
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "1")
        assert dispatch_signature() == "per-step"


class TestDevicePrefetcher:
    def test_yields_in_order(self):
        with DevicePrefetcher(10, lambda i: i * i, depth=2) as pf:
            assert list(pf) == [i * i for i in range(10)]

    def test_bounded_depth(self):
        import threading

        staged = []
        gate = threading.Event()

        def stage(i):
            staged.append(i)
            return i

        pf = DevicePrefetcher(10, stage, depth=2)
        try:
            assert next(pf) == 0
            gate.wait(0.3)  # give the producer time to overrun if it could
            # one consumed + at most depth in the queue + one in flight
            assert len(staged) <= 4
        finally:
            pf.close()

    def test_stage_exception_reraised_in_consumer(self):
        def stage(i):
            if i == 3:
                raise ValueError("bad batch")
            return i

        pf = DevicePrefetcher(10, stage, depth=2)
        try:
            got = []
            with pytest.raises(ValueError, match="bad batch"):
                for v in pf:
                    got.append(v)
            assert got == [0, 1, 2]  # everything before the fault arrived
        finally:
            pf.close()

    def test_simulated_kill_crosses_thread(self):
        """SimulatedKill is a BaseException — 'except Exception' would miss
        it; the prefetcher must still deliver it to the consumer."""

        def stage(i):
            if i == 1:
                raise SimulatedKill("mid-staging")
            return i

        pf = DevicePrefetcher(5, stage, depth=2)
        try:
            with pytest.raises(SimulatedKill):
                list(pf)
        finally:
            pf.close()

    def test_close_unblocks_parked_producer(self):
        """A producer blocked on a full queue must exit promptly on close —
        a leaked thread would keep calling stage() on a rolled-back task."""
        pf = DevicePrefetcher(100, lambda i: i, depth=1)
        next(pf)  # let the producer start and fill the queue
        pf.close()
        assert not pf._thread.is_alive()
        with pytest.raises(StopIteration):
            next(pf)


def _pipeline_task(tmp_path, tag, batch_count=6):
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=batch_count),
        chip_range=[4],
        name="pipe-eq",  # same name both arms: identical init PRNG stream
        save_dir=str(tmp_path / tag),
    )


def _run_interval(task, tech, devices, n, window_size):
    task.strategies = {
        len(devices): Strategy(
            executor=tech, apportionment=len(devices), params={},
            runtime=1.0, per_batch_time=0.1,
        )
    }
    task.select_strategy(len(devices))
    tech.execute(task, devices, 0, override_batch_count=n,
                 window_size=window_size)
    ckpt.flush()
    return ckpt.load_arrays(task.ckpt_path)


class TestFusedEquivalence:
    def test_fused_window_matches_per_step_exactly(self, tmp_path, devices8):
        """K=3 fused windows (+ no tail) vs the legacy 1-step loop: same
        final step count, bit-identical parameters."""
        from saturn_tpu.parallel.dp import DataParallel

        devs = devices8[:4]
        ref = _run_interval(
            _pipeline_task(tmp_path, "per-step"), DataParallel(), devs,
            n=6, window_size=1,
        )
        fused = _run_interval(
            _pipeline_task(tmp_path, "fused"), DataParallel(), devs,
            n=6, window_size=3,
        )
        assert int(ref["step"]) == int(fused["step"]) == 6
        assert set(ref) == set(fused)
        for name in ref:
            np.testing.assert_array_equal(ref[name], fused[name], err_msg=name)

    def test_tail_batches_use_exact_fallback(self, tmp_path, devices8):
        """n=5, K=3: one fused window + a 2-batch per-step tail must equal
        the pure per-step run — the tail is the SAME 1-step program."""
        from saturn_tpu.parallel.dp import DataParallel

        devs = devices8[:4]
        ref = _run_interval(
            _pipeline_task(tmp_path, "ref", batch_count=5), DataParallel(),
            devs, n=5, window_size=1,
        )
        mixed = _run_interval(
            _pipeline_task(tmp_path, "mixed", batch_count=5), DataParallel(),
            devs, n=5, window_size=3,
        )
        assert int(mixed["step"]) == 5
        for name in ref:
            np.testing.assert_array_equal(ref[name], mixed[name], err_msg=name)

    def test_midwindow_kill_discards_interval_then_replay_matches(
        self, tmp_path, devices8
    ):
        """SimulatedKill inside the SECOND fused window: the interval leaves
        no checkpoint and no live state (all-or-nothing), and the replay
        from scratch matches the per-step reference bit-for-bit."""
        from saturn_tpu.parallel.dp import DataParallel

        devs = devices8[:4]
        ref = _run_interval(
            _pipeline_task(tmp_path, "ref"), DataParallel(), devs,
            n=6, window_size=1,
        )

        task = _pipeline_task(tmp_path, "killed")
        tech = DataParallel()
        task.strategies = {
            4: Strategy(executor=tech, apportionment=4, params={},
                        runtime=1.0, per_batch_time=0.1)
        }
        task.select_strategy(4)
        bundle = tech.build(task, devs, {})
        real = bundle.fused_compiled(3)
        calls = {"n": 0}

        def killer(state, window):
            calls["n"] += 1
            if calls["n"] == 2:
                raise SimulatedKill("mid-window")
            return real(state, window)

        bundle._fused[3] = killer
        try:
            with pytest.raises(SimulatedKill):
                tech.execute(task, devs, 0, override_batch_count=6,
                             window_size=3)
        finally:
            bundle._fused[3] = real
        ckpt.flush()
        # All-or-nothing: no checkpoint, no cached device state, no realized
        # feedback from the dead attempt.
        assert not task.has_ckpt()
        assert task._live_state is None
        assert task._pending_realized is None

        replay = _run_interval(task, tech, devs, n=6, window_size=3)
        assert int(replay["step"]) == 6
        for name in ref:
            np.testing.assert_array_equal(ref[name], replay[name],
                                          err_msg=name)


@pytest.mark.perf
@pytest.mark.slow
def test_step_pipeline_microbenchmark_runs():
    """`pytest -m perf`: the microbenchmark executes end-to-end and the
    fused+prefetch pipeline is not slower than the per-step loop beyond
    noise. The real perf claim (measurable speedup) is asserted by eye /
    by the driver on the printed JSON — a hard ratio here would flake on
    loaded CI hosts."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks", "step_pipeline.py")],
        capture_output=True, text=True, timeout=480,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "step_pipeline_tokens_per_sec"
    assert out["value"] > 0 and out["per_step"] > 0
    # fused+prefetch must at minimum not regress vs the old hot loop
    assert out["speedup_vs_per_step"] > 0.95


@pytest.mark.slow
def test_orchestrate_equivalent_across_window_caps(tmp_path, devices8,
                                                   monkeypatch):
    """The ISSUE's acceptance run: a seeded 2-task orchestrate under
    SATURN_TPU_MAX_WINDOW=1 vs =4 produces identical final checkpoints and
    the same iteration ledger (all batches retired exactly once)."""
    import saturn_tpu
    from saturn_tpu import HParams, Task, library
    from saturn_tpu.core.mesh import SliceTopology
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    def mk(tag, name, lr):
        return Task(
            get_model=lambda **kw: build_gpt2("test-tiny", **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 8,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=lr, batch_count=8),
            chip_range=[4],
            name=name,
            save_dir=str(tmp_path / tag),
        )

    topo = SliceTopology(devices8)
    library.register_default_library()
    finals = {}
    for cap in ("1", "4"):
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", cap)
        tasks = [mk(f"cap{cap}", "eq-lr3", 1e-3), mk(f"cap{cap}", "eq-lr4", 1e-4)]
        saturn_tpu.search(tasks, technique_names=["dp"], topology=topo)
        saturn_tpu.orchestrate(tasks, interval=30.0, topology=topo,
                               solver_time_limit=5.0)
        for t in tasks:
            assert t.total_batches == 0
            assert t.has_ckpt()
        finals[cap] = {t.name: ckpt.load_arrays(t.ckpt_path) for t in tasks}

    for name in finals["1"]:
        a, b = finals["1"][name], finals["4"][name]
        assert int(a["step"]) == int(b["step"]) == 8
        assert set(a) == set(b)
        for arr in a:
            np.testing.assert_array_equal(a[arr], b[arr],
                                          err_msg=f"{name}/{arr}")
