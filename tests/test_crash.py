"""Crash-safe durability: journal unit tests + the kill-replay harness.

Everything runs hardware-free on the 8 virtual CPU devices from conftest.
The acceptance test at the bottom is the ISSUE's scenario: a 4-job
mixed-priority service run killed at three distinct kill-points
(mid-interval, mid-fsync — with a genuinely torn journal tail — and
post-checkpoint), restarted against the same journal directory each time,
with the asserts that zero admitted jobs are lost, zero durably completed
iterations are re-run (journal sequence numbers are the evidence), and the
corrupt trailing artifacts are quarantined rather than fatal.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.durability import (
    Journal,
    JournalCorruptError,
    build_restore_records,
    recover,
    replay,
    replay_batch_state,
    replay_service_state,
)
from saturn_tpu.resilience import CrashInjector, SimulatedKill, run_to_kill

pytestmark = pytest.mark.crash


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class RecordingTech(BaseTechnique):
    name = "crash-fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.calls.append((task.name, override_batch_count or 1))
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FakeTask:
    """Duck-typed pre-profiled task (admission skips the trial sweep)."""

    def __init__(self, name, total_batches, sizes, tech, pbt=0.001):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {}
        self.chip_range = None
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


# ------------------------------------------------------------------ journal
class TestJournal:
    def test_roundtrip_rotation_and_seq_continuity(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d, segment_max_bytes=512)
        j.log("job_submitted", job="j0001-a", task="a", total_batches=10)
        for _ in range(20):
            j.append("task_progress", task="a", job="j0001-a", batches=1)
        assert j.pending == 20
        j.commit()
        assert j.pending == 0
        j.close()

        segs = [n for n in os.listdir(d) if n.endswith(".jsonl")]
        assert len(segs) >= 2  # 512-byte cap forced at least one rotation
        recs = replay(d, strict=True)
        seqs = [r["seq"] for r in recs]
        assert seqs == list(range(1, len(recs) + 1))  # strictly monotonic

        # a new incarnation continues the sequence, in a FRESH segment
        # (whose segment_open header consumes the next seq itself)
        j2 = Journal(d, segment_max_bytes=512)
        s = j2.log("recovery")
        assert s == seqs[-1] + 2
        j2.close()
        assert replay(d, strict=True)[-1]["seq"] == s

    def test_uncommitted_records_die_with_the_process(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d)
        j.log("a")
        j.append("b")  # never committed — "process dies" here
        recs = replay(d, strict=True)
        assert [r["kind"] for r in recs] == ["segment_open", "a"]

    def test_torn_tail_quarantined_and_seq_resumes(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d)
        j.log("a")
        j.log("b")
        j.close()
        seg = os.path.join(d, "wal-000001.jsonl")
        with open(seg, "ab") as f:
            f.write(b'{"crc":"00000000","data":{},"ki')  # torn append
        with pytest.raises(JournalCorruptError):
            replay(d, strict=True)

        j2 = Journal(d)  # open runs recovery
        assert j2.recovery_report["quarantined"] == [seg + ".corrupt"]
        assert os.path.exists(seg + ".corrupt")
        j2.log("c")
        j2.close()
        recs = replay(d, strict=True)  # strict passes after quarantine
        assert [r["kind"] for r in recs if r["kind"] != "segment_open"] == [
            "a", "b", "c",
        ]

    def test_mid_sequence_corruption_rolls_back_later_segments(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d, segment_max_bytes=256)
        for i in range(12):
            j.log("rec", i=i)
        j.close()
        segs = sorted(n for n in os.listdir(d) if n.endswith(".jsonl"))
        assert len(segs) >= 3
        # flip bytes in the MIDDLE segment: everything after the durable cut
        # must roll back, including structurally-valid later segments
        victim = os.path.join(d, segs[1])
        raw = open(victim, "rb").read()
        open(victim, "wb").write(raw[: len(raw) // 2] + b"XXXX"
                                 + raw[len(raw) // 2 + 4:])
        report = recover(d)
        assert len(report["quarantined"]) >= 2  # victim tail + later segs
        recs = replay(d, strict=True)
        datas = [r["data"]["i"] for r in recs if r["kind"] == "rec"]
        assert datas == list(range(len(datas)))  # a clean prefix, no gaps

    def test_crc_catches_bit_rot(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d)
        j.log("x", payload="hello")
        j.close()
        seg = os.path.join(d, "wal-000001.jsonl")
        raw = open(seg, "rb").read()
        open(seg, "wb").write(raw.replace(b"hello", b"jello"))
        recs = replay(d)  # non-strict: stops at the bad record
        assert all(r["kind"] != "x" for r in recs)


# --------------------------------------------------------------- kill points
class TestCrashInjector:
    def test_fires_on_exact_hit_then_goes_inert(self, tmp_path):
        inj = CrashInjector("post-commit", hit=2)
        j = Journal(str(tmp_path / "wal"), barrier=inj.barrier)
        j.log("a")
        with pytest.raises(SimulatedKill):
            j.log("b")
        assert inj.fired.is_set()
        j.log("c")  # inert after firing: the "dead" process's threads unwind
        assert replay(str(tmp_path / "wal"), strict=True)[-1]["kind"] == "c"

    def test_mid_fsync_kill_tears_the_tail(self, tmp_path):
        d = str(tmp_path / "wal")
        inj = CrashInjector("mid-fsync", hit=1, armed=False)
        j = Journal(d, barrier=inj.barrier)
        j.log("a")  # disarmed: setup commits pass through
        inj.arm()
        with pytest.raises(SimulatedKill):
            j.log("b", payload="x" * 64)
        # the un-fsync'd tail was physically torn: recovery must quarantine
        report = recover(d)
        assert report["quarantined"]
        recs = replay(d, strict=True)
        assert [r["kind"] for r in recs if r["kind"] != "segment_open"] == ["a"]

    def test_seeded_is_deterministic(self):
        a = CrashInjector.seeded(1234, armed=False)
        b = CrashInjector.seeded(1234, armed=False)
        assert (a.point, a.hit) == (b.point, b.hit)


# ------------------------------------------------------- checkpoint satellite
class TestCheckpointCorruption:
    def test_corrupt_npz_quarantined_with_typed_error(self, tmp_path):
        from saturn_tpu.utils import checkpoint as ckpt

        path = str(tmp_path / "state.npz")
        good = {"a": np.arange(4, dtype=np.float32)}
        ckpt.save(path, good)
        assert ckpt.verify(path) is True

        with open(path, "wb") as f:
            f.write(b"PK\x03\x04 this is not a checkpoint")
        assert ckpt.verify(path) is False
        with pytest.raises(ckpt.CheckpointCorruptError) as ei:
            ckpt.restore(path, good)
        assert ei.value.quarantined == path + ".corrupt"
        assert os.path.exists(path + ".corrupt")
        assert not os.path.exists(path)  # recovery falls back to previous

    def test_missing_is_not_corrupt(self, tmp_path):
        from saturn_tpu.utils import checkpoint as ckpt

        with pytest.raises(FileNotFoundError):
            ckpt.restore(str(tmp_path / "never.npz"), {"a": np.zeros(1)})

    def test_publish_hook_fires_after_atomic_rename(self, tmp_path):
        from saturn_tpu.utils import checkpoint as ckpt

        seen = []
        hook = lambda stem, path: seen.append((stem, os.path.exists(path)))
        ckpt.add_publish_hook(hook)
        try:
            ckpt.save(str(tmp_path / "t1.npz"), {"a": np.zeros(2)})
        finally:
            ckpt.remove_publish_hook(hook)
        assert seen == [("t1", True)]


# ---------------------------------------------------------- metrics satellite
class TestMetricsTornTail:
    def test_read_events_skips_and_warns_on_torn_line(self, tmp_path, caplog):
        from saturn_tpu.utils.metrics import read_events

        p = str(tmp_path / "m.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "solve"}) + "\n")
            f.write('{"ts": 2.0, "kind": "inter')  # crashed writer's tail
        with caplog.at_level("WARNING", logger="saturn_tpu"):
            evs = read_events(p)
        assert [e["kind"] for e in evs] == ["solve"]
        assert any("torn" in r.message for r in caplog.records)


# --------------------------------------------------------- evaluator satellite
class TestTrialRetry:
    class FlakyTech(BaseTechnique):
        name = "crash-flaky"
        failures_left = 0

        def execute(self, task, devices, tid, override_batch_count=None):
            pass

        def search(self, task, devices, tid):
            cls = type(self)
            if cls.failures_left > 0:
                cls.failures_left -= 1
                raise RuntimeError("transient flake")
            return {}, 0.001

    def _sweep(self, tmp_path, retries):
        from saturn_tpu import library
        from saturn_tpu.trial_runner import evaluator
        from saturn_tpu.utils.metrics import read_events

        mpath = str(tmp_path / "m.jsonl")
        task = FakeTask("flaky", 10, [], None)
        task.strategies = {}
        task.chip_range = (2,)
        library.register("crash-flaky", self.FlakyTech)
        try:
            evaluator.search(
                [task], technique_names=["crash-flaky"], topology=topo(8),
                metrics_path=mpath, profile_cache=False,
                trial_retries=retries, retry_backoff_s=0.001,
            )
        finally:
            library.deregister("crash-flaky")
        return task, read_events(mpath)

    def test_transient_flake_retried_to_success(self, tmp_path):
        self.FlakyTech.failures_left = 2
        task, evs = self._sweep(tmp_path, retries=2)
        assert task.feasible_strategies()  # third attempt succeeded
        retriesv = [e for e in evs if e["kind"] == "trial_retry"]
        assert len(retriesv) == 2
        assert [e["attempt"] for e in retriesv] == [1, 2]
        # exponential backoff: attempt 2's delay window starts above 1's base
        assert retriesv[1]["backoff_s"] > retriesv[0]["backoff_s"]

    def test_budget_exhaustion_is_infeasible_not_fatal(self, tmp_path):
        self.FlakyTech.failures_left = 99
        task, evs = self._sweep(tmp_path, retries=1)
        assert not task.feasible_strategies()  # recorded infeasible
        assert len([e for e in evs if e["kind"] == "trial_retry"]) == 1
        trial = [e for e in evs if e["kind"] == "trial"]
        assert trial and trial[-1]["feasible"] is False


# ------------------------------------------------------------- batch resume
class TestOrchestrateResume:
    def test_resume_runs_only_undurable_batches(self, tmp_path):
        from saturn_tpu import orchestrate

        d = str(tmp_path / "wal")
        # A prior incarnation durably recorded: 30 of a's 50 batches ran,
        # and b completed outright.
        j = Journal(d)
        j.append("task_progress", task="a", batches=30)
        j.append("task_progress", task="b", batches=40)
        j.append("task_completed", task="b")
        j.commit()
        j.close()

        tech = RecordingTech()
        a = FakeTask("a", 50, [2, 4], tech)
        b = FakeTask("b", 40, [2, 4], tech)
        out = orchestrate([a, b], interval=0.2, topology=topo(8),
                          resume_dir=d)
        assert sorted(out["completed"]) == ["a", "b"]
        # b never re-executed; a ran exactly its un-journaled remainder
        ran = {}
        for name, n in tech.calls:
            ran[name] = ran.get(name, 0) + n
        assert "b" not in ran
        assert ran["a"] == 20

        # the journal now accounts for every iteration exactly once
        state = replay_batch_state(d)
        assert state.progress == {"a": 50, "b": 40}
        assert sorted(state.completed) == ["a", "b"]
        replay(d, strict=True)  # seq chain intact across incarnations

    def test_resume_is_idempotent_when_everything_done(self, tmp_path):
        from saturn_tpu import orchestrate

        d = str(tmp_path / "wal")
        tech = RecordingTech()
        out1 = orchestrate([FakeTask("x", 30, [2], tech)], interval=0.2,
                           topology=topo(8), resume_dir=d)
        assert out1["completed"] == ["x"]
        n_calls = len(tech.calls)
        # same batch re-launched after "crash-after-finish": nothing re-runs
        out2 = orchestrate([FakeTask("x", 30, [2], tech)], interval=0.2,
                           topology=topo(8), resume_dir=d)
        assert out2["completed"] == ["x"]
        assert len(tech.calls) == n_calls


# --------------------------------------------------------------- acceptance
class TestKillReplayAcceptance:
    TOTALS = {"job-a": 90, "job-b": 90, "job-c": 60, "job-d": 60}
    PRIORITIES = {"job-a": 0.0, "job-b": 1.0, "job-c": 2.0, "job-d": 3.0}

    def _provider(self, tech):
        def provide(spec):
            # remaining_batches is the journal-authoritative budget: durably
            # completed iterations are never re-run
            return FakeTask(spec["task"], spec["remaining_batches"],
                            spec["spec"]["sizes"], tech, pbt=0.004)

        return provide

    def _service(self, wal, tech, barrier=None):
        from saturn_tpu.service import SaturnService

        return SaturnService(
            topology=topo(8), interval=0.2, poll_s=0.02,
            durability_dir=wal, task_provider=self._provider(tech),
            crash_barrier=barrier,
        )

    def test_kill_replay_no_lost_jobs_no_rerun_iterations(self, tmp_path):
        from saturn_tpu.service import ServiceClient

        wal = str(tmp_path / "wal")
        tech = RecordingTech(per_batch=0.004)

        # ---- incarnation 1: submit 4 mixed-priority jobs, kill mid-interval
        inj = CrashInjector("mid-interval", hit=2, armed=False)
        svc = self._service(wal, tech, inj.barrier)
        svc.start()
        client = ServiceClient(svc)
        ids = {}
        for name, total in self.TOTALS.items():
            ids[name] = client.submit(
                FakeTask(name, total, [2], tech, pbt=0.004),
                priority=self.PRIORITIES[name],
                spec={"sizes": [2]},
            )
        run_to_kill(inj, svc)
        assert svc.killed

        # ---- incarnation 2: recover, kill mid-fsync (tears the journal)
        inj2 = CrashInjector("mid-fsync", hit=2, armed=False)
        svc2 = self._service(wal, tech, inj2.barrier)
        svc2.start()
        run_to_kill(inj2, svc2)
        assert svc2.killed

        # the torn tail is quarantined on the NEXT open, not fatal
        # ---- incarnation 3: recover, kill post-checkpoint (hit 1: the
        # remaining work may fit one interval)
        inj3 = CrashInjector("post-checkpoint", hit=1, armed=False)
        svc3 = self._service(wal, tech, inj3.barrier)
        assert svc3.journal.recovery_report["quarantined"], (
            "mid-fsync tear must leave a quarantined sidecar"
        )
        svc3.start()
        run_to_kill(inj3, svc3)
        assert svc3.killed

        # ---- final incarnation: no injector, run everything to completion
        svc4 = self._service(wal, tech)
        svc4.start()
        client4 = ServiceClient(svc4)
        try:
            outs = {n: client4.wait(j, timeout=120) for n, j in ids.items()}
        finally:
            svc4.stop(timeout=60)

        # 1. zero admitted jobs lost: every original job id reaches DONE
        #    under the SAME id it was submitted with
        assert all(o["state"] == "DONE" for o in outs.values()), outs
        assert {o["job_id"] for o in outs.values()} == set(ids.values())

        # 2. journal integrity survives three kills: strict replay verifies
        #    every CRC and that seq is strictly monotonic, gap-free, across
        #    all four incarnations
        recs = replay(wal, strict=True)
        seqs = [r["seq"] for r in recs]
        assert seqs == sorted(set(seqs))

        # 3. zero durably completed iterations re-run: per job, journaled
        #    realized batches sum to EXACTLY the submitted budget — never
        #    more (a double-count would re-run or over-count work)
        progress = {}
        for r in recs:
            if r["kind"] == "task_progress":
                progress[r["data"]["task"]] = (
                    progress.get(r["data"]["task"], 0) + r["data"]["batches"]
                )
        assert progress == self.TOTALS, progress

        # 4. the crashes actually cost something and recovery re-admitted:
        #    at least one incarnation resurrected live jobs
        assert any(r["kind"] == "job_recovered" for r in recs)
        recoveries = [r for r in recs if r["kind"] == "recovery"]
        assert len(recoveries) == 4  # one per incarnation
        assert [r["data"]["incarnation"] for r in recoveries] == [1, 2, 3, 4]

        # 5. corrupt trailing artifacts were quarantined, not fatal
        assert any(n.endswith(".corrupt") or ".corrupt." in n
                   for n in os.listdir(wal))

        # 6. every job's terminal DONE verdict is journaled
        done = {r["data"]["job"] for r in recs
                if r["kind"] == "job_state" and r["data"]["state"] == "DONE"}
        assert done == set(ids.values())

    def test_recovery_without_provider_refuses_to_drop_jobs(self, tmp_path):
        from saturn_tpu.service import ServiceClient

        wal = str(tmp_path / "wal")
        tech = RecordingTech()
        inj = CrashInjector("mid-interval", hit=1, armed=False)
        svc = self._service(wal, tech, inj.barrier)
        svc.start()
        ServiceClient(svc).submit(FakeTask("orphan", 200, [2], tech),
                                  spec={"sizes": [2]})
        run_to_kill(inj, svc)
        from saturn_tpu.service import SaturnService

        with pytest.raises(RuntimeError, match="task_provider"):
            SaturnService(topology=topo(8), durability_dir=wal)

    def test_restore_records_rebuild_remaining_budget(self, tmp_path):
        """Unit-level recovery check: journal says 25 of 60 batches are
        durable -> the restored record re-enters QUEUED with 35 remaining."""
        wal = str(tmp_path / "wal")
        j = Journal(wal)
        j.append("job_submitted", job="j0001-t", task="t", priority=1.0,
                 max_retries=1, total_batches=60, spec={"sizes": [2]})
        j.append("job_state", job="j0001-t", state="PROFILING")
        j.append("job_state", job="j0001-t", state="SCHEDULED")
        j.append("job_state", job="j0001-t", state="RUNNING")
        j.append("task_progress", task="t", job="j0001-t", batches=25)
        j.commit()
        j.close()

        state = replay_service_state(wal)
        assert state.jobs["j0001-t"].realized == 25
        assert state.jobs["j0001-t"].remaining == 35

        tech = RecordingTech()
        recs = build_restore_records(state, self._provider_check(tech))
        (rec,) = recs
        assert rec.job_id == "j0001-t"
        assert rec.state.value == "QUEUED"
        assert rec.requeues == 1  # was RUNNING: counts as a requeue
        assert rec.task.total_batches == 35

    def _provider_check(self, tech):
        def provide(spec):
            assert spec["total_batches"] == 60
            assert spec["remaining_batches"] == 35
            return FakeTask(spec["task"], spec["remaining_batches"],
                            spec["spec"]["sizes"], tech)

        return provide
