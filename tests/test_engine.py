"""Engine + orchestrator control-plane tests with fake (hardware-free)
techniques: forecast arithmetic, dependency gating, interval looping."""

import threading
import time

import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.executor import engine
from saturn_tpu.executor.orchestrator import orchestrate
from saturn_tpu.solver.milp import solve


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class RecordingTech(BaseTechnique):
    """Sleeps per batch; records (task, block-size, batches, thread) calls."""

    name = "fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        time.sleep(self.per_batch * (override_batch_count or 1))
        with self.lock:
            self.calls.append(
                (task.name, len(devices), override_batch_count, time.monotonic())
            )

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FakeTask:
    def __init__(self, name, total_batches, sizes, tech, pbt=0.001):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


class TestForecast:
    def test_budget_and_completion(self):
        tech = RecordingTech()
        t1 = FakeTask("a", total_batches=10, sizes=[4], tech=tech, pbt=1.0)
        t2 = FakeTask("b", total_batches=100, sizes=[4], tech=tech, pbt=1.0)
        plan = solve([t1, t2], topo(8), ordering_slack=0.0)
        run, batches, completed = engine.forecast([t1, t2], interval=50.0, plan=plan)
        assert t1 in run and batches["a"] == 10  # capped at remaining
        assert t1 in completed
        assert t2 in run and batches["b"] <= 50
        assert t2 not in completed
        # online re-estimation decremented remaining work
        assert t1.total_batches == 0
        assert t2.total_batches == 100 - batches["b"]
        assert t2.strategies[4].runtime == pytest.approx(t2.total_batches * 1.0)

    def test_slow_task_still_progresses(self):
        """A task whose per-batch time exceeds the interval must get >= 1
        batch — otherwise orchestrate() livelocks re-solving forever."""
        tech = RecordingTech()
        t = FakeTask("slow", total_batches=3, sizes=[8], tech=tech, pbt=2000.0)
        plan = solve([t], topo(8))
        run, batches, _ = engine.forecast([t], interval=1000.0, plan=plan)
        assert t in run and batches["slow"] == 1

    def test_task_beyond_interval_skipped(self):
        tech = RecordingTech()
        t1 = FakeTask("a", 10, [8], tech, pbt=10.0)  # 100s job
        t2 = FakeTask("b", 10, [8], tech, pbt=10.0)
        plan = solve([t1, t2], topo(8), ordering_slack=0.0)
        run, batches, _ = engine.forecast([t1, t2], interval=50.0, plan=plan)
        # only the first-scheduled task fits in the 50s interval
        assert len(run) == 1


class TestExecute:
    def test_dependency_ordering(self):
        """Tasks sharing a block must run in plan order, not concurrently."""
        tech = RecordingTech(per_batch=0.005)
        t1 = FakeTask("a", 5, [8], tech, pbt=1.0)
        t2 = FakeTask("b", 5, [8], tech, pbt=1.0)
        plan = solve([t1, t2], topo(8), ordering_slack=0.0)
        run, batches, _ = engine.forecast([t1, t2], interval=100.0, plan=plan)
        assert len(run) == 2
        engine.execute(run, batches, 100.0, plan, topo(8))
        order = {name: ts for name, _, _, ts in tech.calls}
        dep = plan.dependencies
        later = "a" if dep["a"] else "b"
        earlier = "b" if later == "a" else "a"
        assert order[earlier] < order[later]

    def test_parallel_disjoint_blocks(self):
        tech = RecordingTech(per_batch=0.01)
        t1 = FakeTask("a", 5, [4], tech, pbt=1.0)
        t2 = FakeTask("b", 5, [4], tech, pbt=1.0)
        plan = solve([t1, t2], topo(8), ordering_slack=0.0)
        run, batches, _ = engine.forecast([t1, t2], interval=100.0, plan=plan)
        engine.execute(run, batches, 100.0, plan, topo(8))
        assert len(tech.calls) == 2
        assert {c[1] for c in tech.calls} == {4}

    def test_error_propagates(self):
        class Exploding(RecordingTech):
            def execute(self, *a, **k):
                raise RuntimeError("boom")

        tech = Exploding()
        t1 = FakeTask("a", 5, [4], tech, pbt=1.0)
        plan = solve([t1], topo(8))
        run, batches, _ = engine.forecast([t1], 100.0, plan)
        with pytest.raises(RuntimeError, match="interval execution failed"):
            engine.execute(run, batches, 100.0, plan, topo(8))


class TestOrchestrate:
    def test_runs_all_to_completion(self):
        tech = RecordingTech(per_batch=0.0005)
        tasks = [
            FakeTask(f"t{i}", total_batches=20, sizes=[2, 4], tech=tech, pbt=0.5)
            for i in range(4)
        ]
        orchestrate(tasks, interval=6.0, topology=topo(8), solver_time_limit=5.0)
        done = {}
        for name, _, n, _ in tech.calls:
            done[name] = done.get(name, 0) + n
        assert done == {f"t{i}": 20 for i in range(4)}

    def test_multi_interval_progress(self):
        """Work larger than one interval completes over several rounds."""
        tech = RecordingTech(per_batch=0.0005)
        tasks = [FakeTask("big", total_batches=30, sizes=[8], tech=tech, pbt=1.0)]
        orchestrate(tasks, interval=10.0, topology=topo(8), solver_time_limit=2.0)
        total = sum(n for _, _, n, _ in tech.calls)
        assert total == 30
        assert len(tech.calls) >= 3  # 30 batches at 1s/batch vs 10s intervals

    def test_unprofiled_task_raises(self):
        t = FakeTask("a", 5, [], RecordingTech())
        with pytest.raises(ValueError, match="no profiled strategies"):
            orchestrate([t], topology=topo(8))


# Borrow the REAL feedback implementation so these tests exercise the code
# the orchestrator runs, not a test-double reimplementation.
from saturn_tpu.core.task import Task as _RealTask  # noqa: E402

FakeTask.EWMA_ALPHA = _RealTask.EWMA_ALPHA
FakeTask.note_realized_per_batch = _RealTask.note_realized_per_batch
FakeTask.apply_realized_feedback = _RealTask.apply_realized_feedback


class NotingTech(RecordingTech):
    """RecordingTech that also reports its true per-batch time, the way
    SPMDTechnique.execute does at the end of every interval."""

    def execute(self, task, devices, tid, override_batch_count=None):
        super().execute(task, devices, tid, override_batch_count)
        task.note_realized_per_batch(self.per_batch)


class WindowedTech(RecordingTech):
    """A technique advertising the round-10 fused-window execute contract."""

    supports_windows = True

    def __init__(self, per_batch=0.001, fail_on_window=None):
        super().__init__(per_batch)
        self.fail_on_window = fail_on_window

    def execute(self, task, devices, tid, override_batch_count=None,
                window_size=None):
        from saturn_tpu.resilience.faults import PreemptedError

        n = override_batch_count or 1
        k = max(1, int(window_size or 1))
        # Window-granular dispatch loop: a preemption mid-interval leaves
        # whole windows retired but NO durable progress (no checkpoint) —
        # exactly what SPMDTechnique.execute does.
        for w in range((n + k - 1) // k):
            if self.fail_on_window == w:
                raise PreemptedError(f"chips revoked in window {w}")
            time.sleep(self.per_batch * min(k, n - w * k))
        with self.lock:
            self.calls.append(
                (task.name, len(devices), override_batch_count, window_size)
            )


class TestWindowPlumbing:
    """Round 10: the engine picks K from the interval batch budget and
    passes it only to techniques that advertise the windowed contract."""

    def test_pick_window_follows_budget_and_cap(self, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "4")
        assert engine.pick_window(100) == 4
        assert engine.pick_window(3) == 3
        assert engine.pick_window(1) == 1

    def test_execute_kwargs_gated_on_supports_windows(self):
        assert engine._execute_kwargs(RecordingTech(), 16) == {}
        kw = engine._execute_kwargs(WindowedTech(), 16)
        assert kw == {"window_size": engine.pick_window(16)}

    def test_engine_passes_window_size_to_windowed_tech(self, monkeypatch):
        monkeypatch.setenv("SATURN_TPU_MAX_WINDOW", "4")
        tech = WindowedTech(per_batch=0.001)
        t = FakeTask("a", 10, [4], tech, pbt=1.0)
        plan = solve([t], topo(8))
        run, batches, _ = engine.forecast([t], 100.0, plan)
        engine.execute(run, batches, 100.0, plan, topo(8))
        (_, _, n, window) = tech.calls[0]
        assert window == engine.pick_window(n)

    def test_bare_signature_tech_still_runs(self):
        """RecordingTech has the pre-round-10 execute signature — the engine
        must not pass it the window kwarg (plugin compatibility)."""
        tech = RecordingTech()
        t = FakeTask("a", 5, [4], tech, pbt=1.0)
        plan = solve([t], topo(8))
        run, batches, _ = engine.forecast([t], 100.0, plan)
        engine.execute(run, batches, 100.0, plan, topo(8))
        assert len(tech.calls) == 1


class TestWindowGranularRollback:
    """rollback_forecast with the fused window pipeline (satellite of round
    10): an interval preempted MID-WINDOW is all-or-nothing — the rollback
    must restore the batch budget and every strategy runtime to exactly the
    pre-forecast values, with no partial-window credit."""

    def test_midwindow_preemption_restores_budget_exactly(self):
        tech = WindowedTech(per_batch=0.0, fail_on_window=1)
        t = FakeTask("a", total_batches=10, sizes=[2, 4], tech=tech, pbt=1.0)
        before_budget = t.total_batches
        before_runtimes = {g: s.runtime for g, s in t.strategies.items()}

        plan = solve([t], topo(8), ordering_slack=0.0)
        run, batches, _ = engine.forecast([t], interval=100.0, plan=plan)
        assert t.total_batches == before_budget - batches["a"]  # pre-deducted

        from saturn_tpu.resilience.faults import PreemptedError

        # Preemption is NOT an error under the "raise" policy: the engine
        # hands it back for the orchestrator's requeue path to roll back.
        errors = engine.execute(run, batches, 100.0, plan, topo(8))
        assert isinstance(errors["a"], PreemptedError)
        assert not tech.calls  # window 1 died before the interval recorded

        engine.rollback_forecast(t, batches["a"])
        assert t.total_batches == before_budget
        for g, s in t.strategies.items():
            assert s.runtime == pytest.approx(before_runtimes[g])

    def test_rollback_is_inverse_of_forecast_for_partial_interval(self):
        """Forecast caps an interval below the remaining budget; rollback of
        that partial deduction must also be exact."""
        tech = WindowedTech(per_batch=0.0)
        t = FakeTask("a", total_batches=100, sizes=[4], tech=tech, pbt=1.0)
        plan = solve([t], topo(8))
        run, batches, _ = engine.forecast([t], interval=50.0, plan=plan)
        assert 0 < batches["a"] < 100
        engine.rollback_forecast(t, batches["a"])
        assert t.total_batches == 100
        assert t.strategies[4].runtime == pytest.approx(100 * 1.0)


class TestRaceGuard:
    """engine._check_disjoint: overlapping blocks without an ordering
    dependency must be refused before any program launches."""

    def test_racy_plan_refused(self):
        from saturn_tpu.core.mesh import Block
        from saturn_tpu.solver.milp import Assignment, Plan

        tech = RecordingTech()
        t1 = FakeTask("a", 4, [4], tech)
        t2 = FakeTask("b", 4, [4], tech)
        plan = Plan(
            assignments={
                "a": Assignment(4, Block(0, 4), 0.0, 1.0),
                "b": Assignment(4, Block(0, 4), 0.0, 1.0),  # same block!
            },
            makespan=1.0,
            dependencies={"a": [], "b": []},  # ...and no ordering edge
        )
        with pytest.raises(RuntimeError, match="races"):
            engine.execute([t1, t2], {"a": 4, "b": 4}, 10.0, plan, topo(8))
        assert not tech.calls  # nothing launched

    def test_chain_serialized_overlap_allowed(self):
        """a->b->c serializes (a, c) transitively — no direct edge needed."""
        from saturn_tpu.core.mesh import Block
        from saturn_tpu.solver.milp import Assignment, Plan

        tech = RecordingTech()
        tasks = [FakeTask(n, 4, [4], tech) for n in ("a", "b", "c")]
        plan = Plan(
            assignments={
                n: Assignment(4, Block(0, 4), float(i), 1.0)
                for i, n in enumerate("abc")
            },
            makespan=3.0,
            dependencies={"a": [], "b": ["a"], "c": ["b"]},
        )
        engine.execute(tasks, {n: 4 for n in "abc"}, 10.0, plan, topo(8))
        assert len(tech.calls) == 3

    def test_dependency_cycle_refused(self):
        """A cycle among launched tasks would park their launcher threads
        forever — refuse loudly instead of hanging."""
        from saturn_tpu.core.mesh import Block
        from saturn_tpu.solver.milp import Assignment, Plan

        tech = RecordingTech()
        t1 = FakeTask("a", 4, [4], tech)
        t2 = FakeTask("b", 4, [4], tech)
        plan = Plan(
            assignments={
                "a": Assignment(4, Block(0, 4), 0.0, 1.0),
                "b": Assignment(4, Block(4, 4), 0.0, 1.0),
            },
            makespan=1.0,
            dependencies={"a": ["b"], "b": ["a"]},
        )
        with pytest.raises(RuntimeError, match="cycle"):
            engine.execute([t1, t2], {"a": 4, "b": 4}, 10.0, plan, topo(8))
        assert not tech.calls

    def test_ordered_overlap_allowed(self):
        from saturn_tpu.core.mesh import Block
        from saturn_tpu.solver.milp import Assignment, Plan

        tech = RecordingTech()
        t1 = FakeTask("a", 4, [4], tech)
        t2 = FakeTask("b", 4, [4], tech)
        plan = Plan(
            assignments={
                "a": Assignment(4, Block(0, 4), 0.0, 1.0),
                "b": Assignment(4, Block(0, 4), 1.0, 1.0),
            },
            makespan=2.0,
            dependencies={"a": [], "b": ["a"]},  # serialized: fine
        )
        engine.execute([t1, t2], {"a": 4, "b": 4}, 10.0, plan, topo(8))
        assert len(tech.calls) == 2


class TestEstimateFeedback:
    """Profiled-vs-realized correction (VERDICT r3 #2): the reference logged
    the estimate error and moved on (``executor.py:126-129``); here the
    orchestrator folds realized per-batch time back into the executed
    strategy so resolve()/forecast consume corrected numbers."""

    def test_two_updates_converge_2x_error(self):
        tech = RecordingTech()
        t = FakeTask("a", total_batches=100, sizes=[4], tech=tech, pbt=2.0)
        t.select_strategy(4)
        for _ in range(2):  # two intervals' worth of corrections
            t.note_realized_per_batch(1.0)
            assert t.apply_realized_feedback() is not None
        s = t.strategies[4]
        assert abs(s.per_batch_time - 1.0) < 0.10  # 2x error -> <10%
        assert s.runtime == pytest.approx(s.per_batch_time * t.total_batches)

    def test_apply_without_note_is_noop(self):
        t = FakeTask("a", 10, [4], RecordingTech(), pbt=2.0)
        assert t.apply_realized_feedback() is None
        assert t.strategies[4].per_batch_time == 2.0

    def test_siblings_scale_by_same_ratio(self):
        """Systemic error (contention hits every apportionment alike): the
        correction ratio propagates to sibling strategies, or the re-solve
        would ping-pong to whichever sibling kept its optimistic profile."""
        tech = RecordingTech()
        t = FakeTask("a", total_batches=10, sizes=[2, 4, 8], tech=tech,
                     pbt=1.0)
        t.select_strategy(4)
        t.note_realized_per_batch(3.0)  # 3x slower than profiled
        old, new = t.apply_realized_feedback()
        ratio = new / old
        for g in (2, 8):
            s = t.strategies[g]
            assert s.per_batch_time == pytest.approx(1.0 * ratio)
            assert s.runtime == pytest.approx(s.per_batch_time * 10)

    def test_alternating_strategies_do_not_compound(self):
        """ADVICE r4: if the re-solve alternates between two strategies with
        strategy-specific (not systemic) errors, cross-corrections must not
        multiply without bound. Anchored replacement keeps each sibling at
        trial_profile x (executed_now / executed_trial), and a strategy
        that has its own measurement is never overwritten by a sibling's."""
        tech = RecordingTech()
        t = FakeTask("a", total_batches=10, sizes=[2, 4], tech=tech, pbt=1.0)
        # Strategy 4 truly runs at 2.0, strategy 2 truly runs at 1.0:
        # alternate executions many times; under compounding the estimates
        # diverge geometrically, under anchoring they stay bounded.
        for _ in range(6):
            t.select_strategy(4)
            t.note_realized_per_batch(2.0)
            t.apply_realized_feedback()
            t.select_strategy(2)
            t.note_realized_per_batch(1.0)
            t.apply_realized_feedback()
        # Each converges to its own realized time (both self-measured, so
        # neither is rescaled by the other's ratio after its first run).
        assert abs(t.strategies[4].per_batch_time - 2.0) < 0.05
        assert abs(t.strategies[2].per_batch_time - 1.0) < 0.05

    def test_never_executed_sibling_tracks_cumulative_ratio(self):
        """A sibling with no measurement of its own follows the executed
        strategy's *cumulative* correction vs its trial profile — replaced
        each time, not compounded across intervals."""
        tech = RecordingTech()
        t = FakeTask("a", total_batches=10, sizes=[2, 4], tech=tech, pbt=1.0)
        t.select_strategy(4)
        for _ in range(5):
            t.note_realized_per_batch(3.0)
            t.apply_realized_feedback()
        s4 = t.strategies[4]
        # executed strategy EWMA-converges to 3.0; sibling = trial x ratio
        expected_sibling = 1.0 * (s4.per_batch_time / 1.0)
        assert t.strategies[2].per_batch_time == pytest.approx(
            expected_sibling
        )
        assert t.strategies[2].per_batch_time < 3.5  # bounded, not 3^5

    def test_note_is_consumed_once(self):
        t = FakeTask("a", 10, [4], RecordingTech(), pbt=2.0)
        t.select_strategy(4)
        t.note_realized_per_batch(1.0)
        assert t.apply_realized_feedback() is not None
        assert t.apply_realized_feedback() is None  # no double-count

    def test_multihost_rejects_drop_and_retry(self, monkeypatch):
        """drop/retry mutate the task set from a per-rank error view —
        multi-host orchestration must refuse them up front."""
        from saturn_tpu.core import distributed

        monkeypatch.setattr(distributed, "is_multihost", lambda: True)
        t = FakeTask("a", 5, [4], RecordingTech())
        for policy in ("drop", "retry"):
            with pytest.raises(ValueError, match="raise"):
                orchestrate([t], topology=topo(8), failure_policy=policy)

    def test_orchestrate_corrects_profile(self, tmp_path):
        """A 1000x-pessimistic profile is pulled toward the realized time
        during the run, and the correction is recorded in metrics."""
        import json

        tech = NotingTech(per_batch=0.0005)
        tasks = [FakeTask("t0", total_batches=20, sizes=[4], tech=tech,
                          pbt=0.5)]
        mpath = str(tmp_path / "metrics.jsonl")
        orchestrate(tasks, interval=4.0, topology=topo(8),
                    solver_time_limit=2.0, metrics_path=mpath)
        s = tasks[0].strategies[4]
        assert s.per_batch_time < 0.2  # moved from 0.5 toward 0.0005
        with open(mpath) as f:
            events = [json.loads(line) for line in f]
        updates = [e for e in events if e["kind"] == "estimate_update"]
        assert updates and updates[0]["profiled_s"] == pytest.approx(0.5)
        assert updates[0]["updated_s"] < 0.2
