"""Training-health guardian (round 13): sentinel, policy, watchdog, plumbing.

Hardware-free units for the on-device numeric sentinel (the fold runs on the
8 virtual CPU devices), the guardian's per-(task, cause) recovery policy
against a real durability journal, the engine's hung-dispatch watchdog with
a deliberately wedged fake technique, the quarantine skip-list's cursor
math, journal replay of ``health_*`` records, the analysis CLI's ``health``
subcommand, and the round's satellite fixes (prefetcher close semantics,
corrupt-sidecar atomicity, the swallowed-exception lint).
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.data.prefetch import DevicePrefetcher
from saturn_tpu.durability import Journal, replay, replay_batch_state
from saturn_tpu.durability.recovery import fold_health_record
from saturn_tpu.executor import engine
from saturn_tpu.health import (
    GuardianConfig,
    HEALTH_EVENT_CODES,
    HungDispatchError,
    NumericFaultError,
    SentinelConfig,
    TrainingGuardian,
)
from saturn_tpu.health import sentinel
from saturn_tpu.solver.milp import Assignment, Plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeDev:
    platform = "cpu"
    device_kind = "fake-cpu"
    process_index = 0


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class FakeTask:
    """Duck-typed pre-profiled task with the real skip-list contract."""

    def __init__(self, name, total_batches, sizes, tech, pbt=0.001,
                 epoch_length=8):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = epoch_length
        self.hints = {}
        self.chip_range = None
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None
        self._quarantined = set()

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length

    def note_realized_per_batch(self, per_batch):
        pass

    def quarantine_batches(self, indices):
        add = {int(i) % self.epoch_length for i in indices}
        if len(self._quarantined | add) >= self.epoch_length:
            raise ValueError(f"task {self.name}: would empty the dataset")
        self._quarantined |= add

    def quarantined_batches(self):
        return tuple(sorted(self._quarantined))


def solo_plan(name, size=4):
    return Plan(
        assignments={name: Assignment(size, Block(0, size), 0.0, 1.0)},
        makespan=1.0,
        dependencies={name: []},
    )


# ----------------------------------------------------------------- sentinel
class TestSentinelFold:
    CFG = SentinelConfig(enabled=True)

    def _report(self, losses, cfg=None, carry=None):
        import jax.numpy as jnp

        if carry is None:
            carry = sentinel.carry_init()
        return np.asarray(sentinel.fold(
            jnp.asarray(carry), jnp.asarray(losses, dtype=jnp.float32),
            cfg or self.CFG,
        ))

    def test_healthy_interval_is_clean_and_preserves_last_loss(self):
        losses = np.asarray([5.5, 5.25, 5.125], dtype=np.float32)
        rep = self._report(losses)
        assert sentinel.inspect(rep) is None
        # the report's last slot IS the old bare readback, bit for bit
        assert np.float32(rep[sentinel.REP_LAST_LOSS]).tobytes() == \
            losses[-1].tobytes()

    def test_nan_detected_with_offset(self):
        rep = self._report([1.0, float("nan"), 1.0])
        cause, off, bad = sentinel.inspect(rep)
        assert (cause, off, bad) == (sentinel.CAUSE_NONFINITE, 1, 1)

    def test_inf_detected(self):
        rep = self._report([1.0, 1.0, float("inf")])
        cause, off, bad = sentinel.inspect(rep)
        assert (cause, off, bad) == (sentinel.CAUSE_NONFINITE, 2, 1)

    def test_multiple_bad_steps_counted_first_reported(self):
        rep = self._report([float("nan"), 1.0, float("inf")])
        cause, off, bad = sentinel.inspect(rep)
        assert (cause, off, bad) == (sentinel.CAUSE_NONFINITE, 0, 2)

    def test_spike_detection_opt_in(self):
        cfg = SentinelConfig(enabled=True, spike_factor=3.0, warmup_steps=2)
        losses = [1.0, 1.0, 1.0, 50.0]
        rep = self._report(losses, cfg=cfg)
        cause, off, bad = sentinel.inspect(rep)
        assert (cause, off) == (sentinel.CAUSE_SPIKE, 3)
        # spikes are policy: the default config must NOT flag the same data
        assert sentinel.inspect(self._report(losses)) is None

    def test_bad_step_does_not_advance_ewma(self):
        rep = self._report([2.0, float("nan")])
        assert rep[sentinel.REP_EWMA] == pytest.approx(2.0)
        assert rep[sentinel.REP_STEPS] == 1.0  # only the healthy step folded

    def test_carry_persists_across_intervals(self):
        rep1 = self._report([1.0, 1.0])
        rep2 = self._report([1.0, 1.0], carry=rep1[:2])
        assert rep2[sentinel.REP_STEPS] == 4.0

    def test_poison_overrides_by_step_and_batch(self):
        # step-keyed override at interval offset 1
        pos, vals = sentinel.poison_overrides(
            {"steps": {1: float("nan")}}, 4, lambda j: j + 4
        )
        assert list(pos) == [1] and np.isnan(vals[0])
        # batch-keyed override follows the DATASET index (j + 4), so batch 6
        # lands at interval offset 2 — persistent poison survives cursor moves
        pos2, vals2 = sentinel.poison_overrides(
            {"batches": {6: 7.0}}, 4, lambda j: j + 4
        )
        assert list(pos2) == [2] and vals2[0] == 7.0

    def test_no_overrides_returns_none(self):
        assert sentinel.poison_overrides({}, 4, lambda j: j) is None
        assert sentinel.poison_overrides(
            {"batches": {99: 1.0}}, 4, lambda j: j
        ) is None


# ----------------------------------------------------------------- guardian
class TestGuardianPolicy:
    def _fault(self, batches=(2,)):
        return NumericFaultError("sick", 0, sentinel.CAUSE_NONFINITE,
                                 step=1, loss=float("nan"),
                                 batch_indices=batches, bad_count=1)

    def test_backoff_doubles_then_quarantines(self, tmp_path):
        jnl = Journal(str(tmp_path / "wal"))
        g = TrainingGuardian(GuardianConfig(), journal=jnl)
        t = FakeTask("sick", 8, [4], None)
        d1 = g.on_fault(t, self._fault(), 0)
        assert (d1.action, d1.attempt, d1.cooldown) == ("retry", 1, 1)
        assert d1.quarantined == () and t.quarantined_batches() == ()
        d2 = g.on_fault(t, self._fault(), 2)
        assert (d2.action, d2.attempt, d2.cooldown) == ("retry", 2, 2)
        assert d2.quarantined == (2,)
        assert t.quarantined_batches() == (2,)
        jnl.close()
        kinds = [r["kind"] for r in replay(str(tmp_path / "wal"))]
        assert kinds.count("health_fault") == 2
        assert kinds.count("health_backoff") == 2
        assert kinds.count("health_quarantine") == 1

    def test_eviction_past_budget(self, tmp_path):
        g = TrainingGuardian(GuardianConfig(retry_budget=2))
        t = FakeTask("sick", 8, [4], None)
        assert g.on_fault(t, self._fault(), 0).action == "retry"
        assert g.on_fault(t, self._fault(), 2).action == "retry"
        assert g.on_fault(t, self._fault(), 5).action == "evict"

    def test_hung_budget_is_separate_and_smaller(self):
        g = TrainingGuardian(GuardianConfig(hung_budget=1, retry_budget=3))
        t = FakeTask("wedged", 8, [4], None)
        hung = HungDispatchError("wedged", 1.0, 5.0)
        assert g.on_fault(t, hung, 0).action == "retry"
        assert g.on_fault(t, hung, 2).action == "evict"
        # the numeric ledger was never charged
        assert g.on_fault(t, self._fault(), 3).attempt == 1

    def test_note_success_resets_streaks_not_quarantine(self):
        g = TrainingGuardian(GuardianConfig())
        t = FakeTask("sick", 8, [4], None)
        g.on_fault(t, self._fault(), 0)
        g.on_fault(t, self._fault(), 2)
        assert t.quarantined_batches() == (2,)
        g.note_success("sick")
        d = g.on_fault(t, self._fault((3,)), 5)
        assert d.attempt == 1           # streak reset
        assert t.quarantined_batches() == (2,)  # correction persisted

    def test_detach_only_when_grouped(self):
        g = TrainingGuardian(GuardianConfig(detach_after=2))
        t = FakeTask("sick", 8, [4], None)
        assert not g.on_fault(t, self._fault(), 0, in_group=False).detached
        d = g.on_fault(t, self._fault(), 2, in_group=True)
        assert d.detached and "sick" in g.detached_names()

    def test_benched_window_clears_at_resume(self):
        g = TrainingGuardian(GuardianConfig())
        t = FakeTask("sick", 8, [4], None)
        g.on_fault(t, self._fault(), 0)   # cooldown 1 -> resume interval 2
        assert g.benched("sick", 1)
        assert not g.benched("sick", 2)
        assert not g.benched("sick", 3)   # entry cleared
        assert not g.benched("never-faulted", 0)

    def test_quarantine_refused_rather_than_crash(self):
        g = TrainingGuardian(GuardianConfig(quarantine_after=1))
        t = FakeTask("sick", 8, [4], None, epoch_length=2)
        d = g.on_fault(t, self._fault(batches=(0, 1)), 0)
        assert d.action == "retry" and d.quarantined == ()
        assert t.quarantined_batches() == ()

    def test_owns_and_cause(self):
        assert TrainingGuardian.owns(self._fault())
        assert TrainingGuardian.owns(HungDispatchError("x", 1.0, 2.0))
        assert not TrainingGuardian.owns(RuntimeError("plain"))
        assert TrainingGuardian.cause_of(self._fault()) == "nonfinite"
        assert TrainingGuardian.cause_of(
            HungDispatchError("x", 1.0, 2.0)
        ) == "hung_dispatch"

    def test_restore_reapplies_quarantine_and_detach(self):
        g = TrainingGuardian(GuardianConfig())
        t = FakeTask("sick", 8, [4], None)
        g.restore({"sick": [1, 3], "gone": [0]}, ["other"], [t])
        assert t.quarantined_batches() == (1, 3)
        assert g.detached_names() == frozenset({"other"})

    def test_event_codes_are_stable(self):
        assert HEALTH_EVENT_CODES["numeric_fault"] == "SAT-H001"
        assert HEALTH_EVENT_CODES["quarantine"] == "SAT-H010"
        assert HEALTH_EVENT_CODES["evict"] == "SAT-H030"


# ----------------------------------------------------------------- watchdog
class SleepyTech(BaseTechnique):
    name = "sleepy"

    def __init__(self, sleep_s=1.5):
        self.sleep_s = sleep_s

    def execute(self, task, devices, tid, override_batch_count=None):
        time.sleep(self.sleep_s)

    def search(self, task, devices, tid):
        return {}, 0.001


class TestHungDispatchWatchdog:
    def test_wedged_launcher_abandoned_with_typed_error(self):
        t = FakeTask("wedged", 4, [4], SleepyTech(sleep_s=1.5))
        guardian = TrainingGuardian(
            GuardianConfig(watchdog_floor_s=0.15, watchdog_factor=1.0)
        )
        t0 = time.monotonic()
        errors = engine.execute(
            [t], {"wedged": 4}, 10.0, solo_plan("wedged"), topo(8),
            guardian=guardian,
        )
        elapsed = time.monotonic() - t0
        assert isinstance(errors["wedged"], HungDispatchError)
        assert errors["wedged"].deadline_s < errors["wedged"].elapsed_s
        assert t.current_batch == 0        # the abandoned attempt realized nothing
        assert elapsed < 1.4               # did NOT wait out the wedge

    def test_watchdog_off_waits_for_completion(self):
        t = FakeTask("slowpoke", 2, [4], SleepyTech(sleep_s=0.05))
        guardian = TrainingGuardian(GuardianConfig(watchdog=False))
        errors = engine.execute(
            [t], {"slowpoke": 2}, 10.0, solo_plan("slowpoke"), topo(8),
            guardian=guardian,
        )
        assert errors == {}
        assert t.current_batch == 2

    def test_deadline_rule(self):
        g = TrainingGuardian(
            GuardianConfig(watchdog_floor_s=60.0, watchdog_factor=8.0)
        )
        assert g.window_deadline_s(10.0) == pytest.approx(140.0)
        assert g.window_deadline_s(0.0) == pytest.approx(60.0)


# ------------------------------------------------- orchestrator integration
class FaultingTech(BaseTechnique):
    """Raises a NumericFaultError on a task's first ``faults`` attempts,
    then runs clean — a deterministic bad batch under rollback."""

    name = "faulting"

    def __init__(self, victim, faults=2, batches=(2,)):
        self.victim = victim
        self.faults = faults
        self.batches = batches
        self.attempts = 0
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        if task.name == self.victim:
            with self.lock:
                self.attempts += 1
                if self.attempts <= self.faults:
                    raise NumericFaultError(
                        task.name, 0, sentinel.CAUSE_NONFINITE, step=0,
                        loss=float("nan"), batch_indices=self.batches,
                        bad_count=1,
                    )
        time.sleep(0.001)

    def search(self, task, devices, tid):
        return {}, 0.001


class TestOrchestratorHealthPath:
    def test_fault_retries_quarantines_and_completes(self, tmp_path):
        from saturn_tpu.executor.orchestrator import orchestrate

        d = str(tmp_path / "wal")
        tech = FaultingTech("sick", faults=2, batches=(2,))
        sick = FakeTask("sick", 6, [4], tech)
        fine = FakeTask("fine", 6, [4], tech)
        out = orchestrate([sick, fine], interval=0.2, topology=topo(8),
                          resume_dir=d)
        assert sorted(out["completed"]) == ["fine", "sick"]
        assert out["failed"] == {}
        assert tech.attempts == 3          # 2 faulted + 1 clean
        assert sick.quarantined_batches() == (2,)
        # the health ledger is durable: a restart would re-apply it
        state = replay_batch_state(d)
        assert state.quarantined == {"sick": [2]}
        kinds = [r["kind"] for r in replay(d)]
        assert "health_quarantine" in kinds and "health_fault" in kinds

    def test_exhausted_budget_evicts_without_poisoning_partner(self, tmp_path):
        from saturn_tpu.executor.orchestrator import orchestrate

        tech = FaultingTech("doomed", faults=99)
        doomed = FakeTask("doomed", 6, [4], tech)
        fine = FakeTask("fine", 6, [4], tech)
        out = orchestrate(
            [doomed, fine], interval=0.2, topology=topo(8),
            health_guardian=TrainingGuardian(
                GuardianConfig(retry_budget=1, backoff_cap=1)
            ),
        )
        assert out["completed"] == ["fine"]
        assert "doomed" in out["failed"]
        assert "NumericFaultError" in out["failed"]["doomed"]


# ------------------------------------------------------- recovery plumbing
class TestHealthRecordFolding:
    def test_quarantine_union_and_subtract(self):
        q, det = {}, []
        assert fold_health_record(
            "health_quarantine", {"task": "a", "indices": [3, 1]}, q, det)
        assert fold_health_record(
            "health_quarantine", {"task": "a", "indices": [1, 5]}, q, det)
        assert q == {"a": [1, 3, 5]}
        assert fold_health_record(
            "health_unquarantine", {"task": "a", "indices": [3]}, q, det)
        assert q == {"a": [1, 5]}
        assert fold_health_record(
            "health_unquarantine", {"task": "a", "indices": None}, q, det)
        assert q == {}

    def test_detach_dedupes(self):
        q, det = {}, []
        fold_health_record("health_detach", {"task": "a"}, q, det)
        fold_health_record("health_detach", {"task": "a"}, q, det)
        assert det == ["a"]

    def test_unknown_kind_is_not_consumed(self):
        assert not fold_health_record("task_progress", {"task": "a"}, {}, [])

    def test_replay_round_trip(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d)
        j.log("health_quarantine", task="a", indices=[2, 4])
        j.log("health_detach", task="b")
        j.log("health_unquarantine", task="a", indices=[4])
        j.close()
        state = replay_batch_state(d)
        assert state.quarantined == {"a": [2]}
        assert state.detached == ["b"]


class TestHealthCLI:
    def _seed(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d)
        j.log("health_fault", task="a", cause="nonfinite", attempt=1)
        j.log("health_quarantine", task="a", indices=[2])
        j.log("health_detach", task="b")
        j.close()
        return d

    def test_report_json(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        d = self._seed(tmp_path)
        assert main(["--json", "health", d]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["quarantined"] == {"a": [2]}
        assert payload["detached"] == ["b"]
        assert payload["faults"] == {"a": {"nonfinite": 1}}
        assert payload["event_codes"]["quarantine"] == "SAT-H010"

    def test_unquarantine_appends_durable_record(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        d = self._seed(tmp_path)
        assert main(["--json", "health", d, "--unquarantine", "a:2"]) == 0
        assert json.loads(capsys.readouterr().out)["quarantined"] == {}
        # the undo is a journal record, visible to the next incarnation
        assert replay_batch_state(d).quarantined == {}

    def test_bad_index_list_is_usage_error(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        d = self._seed(tmp_path)
        assert main(["health", d, "--unquarantine", "a:x,y"]) == 2

    def test_human_report(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        d = self._seed(tmp_path)
        assert main(["health", d]) == 0
        out = capsys.readouterr().out
        assert "a: faults nonfinite" in out and "quarantined batches [2]" in out


# ------------------------------------------------------- satellite fixes
class TestPrefetcherClose:
    def test_pending_producer_error_reraised_at_close(self):
        def stage(i):
            if i == 1:
                raise ValueError("boom in staging")
            return i

        pf = DevicePrefetcher(3, stage, depth=2)
        assert next(pf) == 0
        time.sleep(0.05)  # let the producer post the error
        with pytest.raises(ValueError, match="boom in staging"):
            pf.close()
        pf.close()  # idempotent: the error is consumed, not re-raised again

    def test_close_does_not_mask_inflight_exception(self):
        def stage(i):
            raise ValueError("producer error")

        pf = DevicePrefetcher(2, stage, depth=2)
        time.sleep(0.05)
        masked = False
        try:
            try:
                raise RuntimeError("the real error")
            finally:
                pf.close()   # must NOT replace RuntimeError with ValueError
        except RuntimeError:
            pass
        except ValueError:
            masked = True
        assert not masked

    def test_wedged_producer_does_not_hang_close(self, monkeypatch):
        from saturn_tpu.data import prefetch as pmod

        monkeypatch.setattr(pmod, "_CLOSE_JOIN_S", 0.2)
        release = threading.Event()

        def stage(i):
            release.wait(5.0)
            return i

        pf = DevicePrefetcher(2, stage, depth=1)
        t0 = time.monotonic()
        pf.close()
        assert time.monotonic() - t0 < 2.0
        release.set()


class TestSidecarAtomicity:
    def test_quarantine_leaves_no_tmp_artifacts(self, tmp_path):
        d = str(tmp_path / "wal")
        j = Journal(d)
        j.log("a")
        j.close()
        seg = os.path.join(d, "wal-000001.jsonl")
        with open(seg, "ab") as f:
            f.write(b'{"torn')
        j2 = Journal(d)   # open runs recovery -> sidecar quarantine
        j2.close()
        names = os.listdir(d)
        assert any(".corrupt" in n for n in names)
        assert not any(n.endswith(".tmp") for n in names)


class TestSwallowLint:
    def _mod(self):
        spec = importlib.util.spec_from_file_location(
            "lint_under_test", os.path.join(REPO, "tools", "lint.py")
        )
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        return m

    def test_silent_swallow_flagged(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "try:\n    work()\nexcept Exception:\n    pass\n"
        )
        m = self._mod()
        found = m._swallow_findings(roots=(str(tmp_path),))
        assert len(found) == 1 and found[0]["line"] == 3

    def test_logged_or_reraised_is_clean(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text(
            "try:\n    work()\nexcept Exception:\n"
            "    logger.warning('x')\n"
            "try:\n    work()\nexcept Exception:\n    raise\n"
            "try:\n    work()\nexcept Exception as e:\n    errs['k'] = e\n"
            "try:\n    work()\nexcept ValueError:\n    pass\n"  # narrow: fine
        )
        m = self._mod()
        assert m._swallow_findings(roots=(str(tmp_path),)) == []

    def test_guarded_packages_are_clean(self):
        m = self._mod()
        assert m._swallow_findings() == []
