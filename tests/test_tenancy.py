"""Multi-tenant control plane: fair-share admission, quotas, replica
leases with epoch fencing, and compile-ahead.

Unit layers (TenantLedger / ReplicaLease / CompileAheadPool) run pure;
the integration layers drive the real service loop on the 8 virtual CPU
devices, the real gateway over loopback TCP, and the netchaos proxy for
the replica-failover acceptance campaign: a replica killed mid-ACK must
yield zero lost jobs and zero duplicate admissions against the shared
journal, with the lease epoch sequence never reusing a fenced epoch.
"""

import json
import threading
import time
from types import SimpleNamespace

import pytest

from saturn_tpu.analysis.cli import main as cli_main
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.durability.recovery import replay_service_state
from saturn_tpu.resilience.crash import CrashInjector
from saturn_tpu.resilience.netchaos import NetChaosProxy, single_fault_spec
from saturn_tpu.service import (
    GatewayClient,
    GatewayError,
    GatewayServer,
    SaturnService,
)
from saturn_tpu.service.admission import ADMIT, DEFER
from saturn_tpu.service.gateway import protocol
from saturn_tpu.service.queue import JobRequest
from saturn_tpu.tenancy import (
    DEFAULT_TENANT,
    CompileAheadPool,
    LeaseHeld,
    ReplicaLease,
    TenantLedger,
    TenantQuota,
)
from saturn_tpu.twin.arrivals import arrival_stream
from saturn_tpu.utils import aot_cache

pytestmark = pytest.mark.tenancy


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class RecordingTech(BaseTechnique):
    """Sleeps per batch; records (task, block-size) launches."""

    name = "tn-fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.calls.append((task.name, len(devices)))
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FakeTask:
    """Duck-typed pre-profiled task (admission skips the trial sweep)."""

    def __init__(self, name, total_batches, sizes, tech, pbt=0.001):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = {}
        self.chip_range = None
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


class PrewarmTask(FakeTask):
    """FakeTask exposing the compile-ahead hook the service duck-types."""

    def compile_ahead(self, topology):
        return [(f"ca-{self.name}", lambda: f"exe-{self.name}")]


def _provider(tech):
    def provide(payload):
        return FakeTask(
            payload["task"], payload["remaining_batches"],
            payload["spec"]["sizes"], tech, pbt=0.004,
        )

    return provide


def _service(tech, wal=None, barrier=None, start=True, **kw):
    svc = SaturnService(
        topology=topo(8), interval=0.2, poll_s=0.02,
        durability_dir=wal, task_provider=_provider(tech),
        crash_barrier=barrier, health_guardian=False, **kw,
    )
    return svc.start() if start else svc


SPEC = {"sizes": [4, 8]}


class FakeJournal:
    """Capture append()/log() records the way the durable journal would."""

    def __init__(self):
        self.records = []

    def append(self, kind, **data):
        self.records.append((kind, data))

    def log(self, kind, **data):
        self.records.append((kind, data))

    def of(self, kind):
        return [d for k, d in self.records if k == kind]


def _submit_frame(gw, name, tenant=None, dedup_key=None, total=3,
                  session="sess"):
    job = {"name": name, "total_batches": total, "spec": SPEC}
    if tenant is not None:
        job["tenant"] = tenant
    frame = {"op": "submit", "job": job}
    if dedup_key is not None:
        frame["dedup_key"] = dedup_key
    return gw._op_submit(frame, session, time.monotonic())


# ------------------------------------------------------------ tenant ledger
class TestTenantLedger:
    def test_quota_resolution_and_defaults(self):
        led = TenantLedger({"paid": TenantQuota(max_live_jobs=4, weight=2.0)})
        assert led.quota("paid").max_live_jobs == 4
        assert led.quota("unknown").max_live_jobs is None
        assert led.quota(None).weight == 1.0
        assert led.resolve(None) == DEFAULT_TENANT
        assert led.resolve("") == DEFAULT_TENANT
        assert led.resolve("acme") == "acme"

    def test_charge_accumulates_and_journals(self):
        led = TenantLedger()
        led.journal = jnl = FakeJournal()
        assert led.charge("acme", 1.5, job="j1") == pytest.approx(1.5)
        assert led.charge("acme", 0.5, job="j2") == pytest.approx(2.0)
        assert led.charged("acme") == pytest.approx(2.0)
        assert led.charged("other") == 0.0
        charges = jnl.of("tenant_charge")
        assert [c["tenant"] for c in charges] == ["acme", "acme"]
        assert sum(c["chip_s"] for c in charges) == pytest.approx(2.0)

    def test_budget_exhaustion(self):
        led = TenantLedger({"meter": TenantQuota(chip_seconds=1.0)})
        assert not led.budget_exhausted("meter")
        led.charge("meter", 0.6)
        assert not led.budget_exhausted("meter")
        led.charge("meter", 0.4)  # >= is exhausted
        assert led.budget_exhausted("meter")
        led.charge("unlimited", 1e9)
        assert not led.budget_exhausted("unlimited")

    def test_fair_share_targets_and_multiplier(self):
        led = TenantLedger({"big": TenantQuota(weight=1.0),
                            "small": TenantQuota(weight=1.0)})
        live = {"big": 4, "small": 1}
        # Equal weights, 5 live jobs: each is entitled to 2.5.
        assert led.fair_target("big", live) == pytest.approx(2.5)
        assert led.over_fair_share("big", live)
        assert not led.over_fair_share("small", live)
        assert led.over_share_tenants(live) == {"big"}
        m_big = led.fair_share_multiplier("big", live)
        m_small = led.fair_share_multiplier("small", live)
        assert m_big < 1.0 < m_small
        # Clamp band: neither direction can zero out (or dominate) the
        # solver's priority/deadline weighting.
        crowd = {"hog": 1000}
        crowd.update({f"t{i}": 1 for i in range(7)})
        assert led.fair_share_multiplier("hog", crowd) == 0.25
        assert led.fair_share_multiplier("quiet",
                                         {"hog": 1000, "quiet": 1}) == 4.0

    def test_weighted_entitlement(self):
        led = TenantLedger({"gold": TenantQuota(weight=3.0),
                            "bronze": TenantQuota(weight=1.0)})
        live = {"gold": 3, "bronze": 1}
        # gold's weighted slice of 4 live jobs is 3 — it is AT share.
        assert led.fair_target("gold", live) == pytest.approx(3.0)
        assert not led.over_fair_share("gold", live)
        assert not led.over_fair_share("bronze", live)

    def test_idle_tenant_counts_as_joining(self):
        led = TenantLedger()
        live = {"busy": 4}
        # An idle tenant's entitlement is computed as if it joined.
        assert led.fair_target("idle", live) == pytest.approx(2.0)
        assert not led.over_fair_share("idle", live)

    def test_restore_replaces_not_adds(self):
        led = TenantLedger()
        led.charge("acme", 5.0)
        led.restore({"acme": 2.0, "zeta": 1.0})
        assert led.charged("acme") == pytest.approx(2.0)
        assert led.charged("zeta") == pytest.approx(1.0)
        # Replaying the same fold twice must not double anything.
        led.restore({"acme": 2.0, "zeta": 1.0})
        assert led.charged("acme") == pytest.approx(2.0)

    def test_snapshot_shape(self):
        led = TenantLedger({"acme": TenantQuota(max_inflight=2)})
        led.note_admit("acme")
        led.note_shed("acme")
        led.charge("acme", 1.0)
        snap = led.snapshot()["acme"]
        assert snap["admitted"] == 1 and snap["shed"] == 1
        assert snap["charged_chip_s"] == pytest.approx(1.0)
        assert snap["max_inflight"] == 2


# ------------------------------------------------------------ replica lease
class TestReplicaLease:
    def test_acquire_renew_check(self):
        lease = ReplicaLease(ttl_s=30.0)
        e1 = lease.ensure("gw-a")
        assert e1 == 1 and lease.owner == "gw-a"
        assert lease.ensure("gw-a") == 1  # renew, same epoch
        assert lease.check("gw-a", e1)
        assert not lease.check("gw-b", e1)
        assert not lease.check("gw-a", e1 + 1)

    def test_held_by_live_peer_raises(self):
        lease = ReplicaLease(ttl_s=30.0)
        lease.ensure("gw-a")
        with pytest.raises(LeaseHeld) as ei:
            lease.ensure("gw-b")
        assert ei.value.holder == "gw-a"
        assert ei.value.retry_after_s > 0

    def test_mark_dead_allows_takeover_and_fences(self):
        lease = ReplicaLease(ttl_s=30.0)
        e1 = lease.ensure("gw-a")
        lease.mark_dead("gw-a")
        # mark_dead alone does NOT advance the epoch — only the
        # successor's acquisition fences the dead replica's stragglers.
        assert lease.check("gw-a", e1)
        e2 = lease.ensure("gw-b")
        assert e2 == e1 + 1
        assert not lease.check("gw-a", e1)  # fenced
        assert lease.check("gw-b", e2)

    def test_ttl_expiry_allows_takeover(self):
        lease = ReplicaLease(ttl_s=0.05)
        e1 = lease.ensure("gw-a")
        time.sleep(0.08)
        e2 = lease.ensure("gw-b")
        assert e2 == e1 + 1 and not lease.check("gw-a", e1)

    def test_release_then_reacquire(self):
        lease = ReplicaLease(ttl_s=30.0)
        lease.ensure("gw-a")
        lease.release("gw-a")
        assert lease.owner is None
        assert lease.ensure("gw-b") == 2

    def test_acquisitions_journal_epoch_owner(self):
        jnl = FakeJournal()
        lease = ReplicaLease(jnl, ttl_s=30.0)
        lease.ensure("gw-a")
        lease.ensure("gw-a")  # renew: no new record
        lease.mark_dead("gw-a")
        lease.ensure("gw-b")
        recs = jnl.of("gateway_lease")
        assert [(r["epoch"], r["owner"]) for r in recs] == \
            [(1, "gw-a"), (2, "gw-b")]
        assert recs[1]["prev_owner"] == "gw-a"
        # Epochs are minted exactly once — unique across the history.
        epochs = [e for e, _, _ in lease.history]
        assert len(epochs) == len(set(epochs))

    def test_seeded_epoch_never_reuses_fenced_epochs(self):
        # A restarted control plane seeds from the journaled max epoch.
        lease = ReplicaLease(ttl_s=30.0, epoch=7)
        assert lease.ensure("gw-c") == 8


# --------------------------------------------------------- compile-ahead pool
class TestCompileAheadPool:
    def test_prewarm_acquire_hit_and_ledger(self):
        jnl = FakeJournal()
        pool = CompileAheadPool(workers=2, journal=jnl)
        try:
            assert pool.prewarm("k1", lambda: "exe-1", job="j1",
                                tenant="acme")
            assert pool.wait_idle(timeout=5.0)
            assert pool.acquire("k1") == "exe-1"
            led = pool.ledger()
            assert led["requested"] == 1 and led["ready"] == 1
            assert led["ahead_hits"] == 1 and led["hit_rate"] == 1.0
            statuses = [d["status"] for d in jnl.of("compile_ahead")]
            assert statuses == ["requested", "ready", "hit"]
        finally:
            pool.close()

    def test_duplicate_prewarm_suppressed(self):
        pool = CompileAheadPool(workers=1)
        try:
            assert pool.prewarm("k", lambda: 1)
            assert not pool.prewarm("k", lambda: 2)
            assert pool.wait_idle(timeout=5.0)
            assert not pool.prewarm("k", lambda: 3)  # already ready
            assert pool.acquire("k") == 1
            assert pool.ledger()["duplicates"] == 2
        finally:
            pool.close()

    def test_thunk_error_is_ledger_entry_not_crash(self):
        pool = CompileAheadPool(workers=1)
        try:
            def boom():
                raise RuntimeError("xla says no")

            assert pool.prewarm("bad", boom)
            assert pool.wait_idle(timeout=5.0)
            assert pool.acquire("bad") is None  # miss, not an exception
            assert "xla says no" in pool.error("bad")
            led = pool.ledger()
            assert led["errors"] == 1 and led["ahead_misses"] == 1
        finally:
            pool.close()

    def test_acquire_waits_out_inflight_compile(self):
        pool = CompileAheadPool(workers=1)
        try:
            pool.prewarm("slow", lambda: (time.sleep(0.2), "done")[1])
            assert pool.acquire("slow", timeout=5.0) == "done"
        finally:
            pool.close()

    def test_unknown_key_is_a_miss(self):
        pool = CompileAheadPool(workers=1)
        try:
            assert pool.acquire("never-asked") is None
            assert pool.ledger()["ahead_misses"] == 1
        finally:
            pool.close()

    def test_closed_pool_refuses_work(self):
        pool = CompileAheadPool(workers=1)
        pool.close()
        assert not pool.prewarm("k", lambda: 1)


# ------------------------------------------------------------- aot warm pool
class TestAotWarmPool:
    def test_prewarm_parks_executable_for_load_or_compile(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        x = jnp.arange(8, dtype=jnp.float32)

        def f(v):
            return v * 2.0

        devices = tuple(jax.devices())
        lowered = jax.jit(f).lower(x)
        try:
            before = aot_cache.stats()
            aot_cache.prewarm(lowered, devices)
            mid = aot_cache.stats()
            assert mid["prewarms"] == before["prewarms"] + 1
            # A fresh lowering of the same program hits the warm pool —
            # zero compile on the dispatch path, even with the on-disk
            # cache disabled (the CPU default).
            exe = aot_cache.load_or_compile(jax.jit(f).lower(x), devices)
            after = aot_cache.stats()
            assert after["warm_hits"] == mid["warm_hits"] + 1
            assert jnp.allclose(exe(x), x * 2.0)
        finally:
            aot_cache.clear_warm()
        # After clear_warm the same key no longer warm-hits.
        cleared = aot_cache.stats()
        aot_cache.load_or_compile(jax.jit(f).lower(x), devices)
        assert aot_cache.stats()["warm_hits"] == cleared["warm_hits"]


# ------------------------------------------------------- ingest params cache
class TestIngestParamsCache:
    def test_concurrent_same_key_loads_once(self, tmp_path, monkeypatch):
        from saturn_tpu.models import ingest

        weights = tmp_path / "w.npz"
        weights.write_bytes(b"placeholder")
        cfg = SimpleNamespace(n_layers=2, d_model=8, vocab_size=16,
                              seq_len=4, rotary=False)
        loads = []
        mapped = {"wte": object()}

        def fake_load(path):
            loads.append(path)
            time.sleep(0.02)  # widen the lookup/load/store race window
            return {"raw": 1}

        monkeypatch.setattr(ingest, "load_torch_state_dict", fake_load)
        monkeypatch.setattr(ingest, "params_from_state_dict",
                            lambda sd, c, **kw: (mapped, []))
        monkeypatch.setattr(ingest, "_cache_key", None)
        monkeypatch.setattr(ingest, "_cache_val", None)

        results = [None] * 8
        barrier = threading.Barrier(len(results))

        def worker(i):
            barrier.wait()
            results[i] = ingest.cached_params_from_path(str(weights), cfg)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(results))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # One load for 8 concurrent callers, and every caller got the
        # identical published (mapped, unused) pair — no torn cache.
        assert len(loads) == 1
        assert all(r is not None and r[0] is mapped for r in results)

    def test_distinct_key_evicts_size_one_cache(self, tmp_path, monkeypatch):
        from saturn_tpu.models import ingest

        weights = tmp_path / "w.npz"
        weights.write_bytes(b"placeholder")
        loads = []
        monkeypatch.setattr(ingest, "load_torch_state_dict",
                            lambda p: loads.append(p) or {"raw": 1})
        monkeypatch.setattr(ingest, "params_from_state_dict",
                            lambda sd, c, **kw: ({"m": len(loads)}, []))
        monkeypatch.setattr(ingest, "_cache_key", None)
        monkeypatch.setattr(ingest, "_cache_val", None)
        cfg_a = SimpleNamespace(n_layers=2, d_model=8, vocab_size=16,
                                seq_len=4, rotary=False)
        cfg_b = SimpleNamespace(n_layers=4, d_model=8, vocab_size=16,
                                seq_len=4, rotary=False)
        ingest.cached_params_from_path(str(weights), cfg_a)
        ingest.cached_params_from_path(str(weights), cfg_a)
        assert len(loads) == 1  # warm hit
        ingest.cached_params_from_path(str(weights), cfg_b)
        assert len(loads) == 2  # different preset shape reloads


# ----------------------------------------------------------- tenant arrivals
class TestTenantArrivals:
    def test_tenant_mix_preserves_primary_draw_order(self):
        kw = dict(base_rate_hz=10.0, burst_rate_hz=50.0, seed=7)
        plain = arrival_stream(80, **kw)
        mixed = arrival_stream(80, tenant_mix={"big": 10.0, "small": 1.0},
                               **kw)
        # Tagging must not perturb the historical trace: same gaps, same
        # priorities, draw for draw.
        assert [(a.at_s, a.priority, a.in_burst) for a in plain] == \
            [(a.at_s, a.priority, a.in_burst) for a in mixed]
        assert all(a.tenant is None for a in plain)
        tenants = [a.tenant for a in mixed]
        assert set(tenants) == {"big", "small"}
        # 10:1 skew shows up in the counts.
        assert tenants.count("big") > 4 * tenants.count("small")
        # Deterministic: same seed, same tags.
        again = arrival_stream(80, tenant_mix={"big": 10.0, "small": 1.0},
                               **kw)
        assert [a.tenant for a in again] == tenants

    def test_tenant_mix_rejects_nonpositive_weights(self):
        with pytest.raises(ValueError):
            arrival_stream(4, base_rate_hz=1.0, burst_rate_hz=2.0,
                           tenant_mix={"a": 0.0})


# ----------------------------------------------- fair-share admission weights
class TestFairShareAdmission:
    def test_over_share_tenant_weight_scaled_down(self):
        tech = RecordingTech()
        led = TenantLedger()
        svc = _service(tech, start=False, tenancy=led)
        for i in range(3):
            svc.queue.submit(JobRequest(
                task=FakeTask(f"bg-{i}", 3, SPEC["sizes"], tech),
                tenant="big",
            ))
        big = svc.queue.submit(JobRequest(
            task=FakeTask("big-new", 3, SPEC["sizes"], tech), tenant="big",
        ))
        small = svc.queue.submit(JobRequest(
            task=FakeTask("small-new", 3, SPEC["sizes"], tech),
            tenant="small",
        ))
        svc.admission.begin_pass()
        dec_big = svc.admission.admit(big, topo(8))
        dec_small = svc.admission.admit(small, topo(8))
        assert dec_big.action == ADMIT and dec_small.action == ADMIT
        # Same priority, no deadline: only the fair-share multiplier
        # separates them — the over-share tenant's new job yields.
        assert dec_big.weight < 1.0 < dec_small.weight
        assert led.snapshot()["big"]["admitted"] == 1

    def test_max_live_jobs_defers_within_one_pass(self):
        tech = RecordingTech()
        led = TenantLedger({"capped": TenantQuota(max_live_jobs=1)})
        svc = _service(tech, start=False, tenancy=led)
        recs = [
            svc.queue.submit(JobRequest(
                task=FakeTask(f"cap-{i}", 3, SPEC["sizes"], tech),
                tenant="capped",
            ))
            for i in range(3)
        ]
        svc.admission.begin_pass()
        decisions = [svc.admission.admit(r, topo(8)) for r in recs]
        # One pass, one slot: the first admits, the burst's siblings
        # defer even though nothing is SCHEDULED yet (the in-pass tally).
        assert [d.action for d in decisions] == [ADMIT, DEFER, DEFER]
        assert "max_live_jobs" in decisions[1].reason

    def test_budget_exhausted_rejects_before_profiling(self):
        tech = RecordingTech()
        led = TenantLedger({"meter": TenantQuota(chip_seconds=1.0)})
        led.charge("meter", 2.0)
        svc = _service(tech, start=False, tenancy=led)
        rec = svc.queue.submit(JobRequest(
            task=FakeTask("metered", 3, SPEC["sizes"], tech), tenant="meter",
        ))
        svc.admission.begin_pass()
        dec = svc.admission.admit(rec, topo(8))
        assert dec.action == "reject"
        assert "budget exhausted" in dec.reason


# ------------------------------------------------------ gateway tenant window
class TestGatewayTenantWindow:
    def test_bursty_shed_quiet_untouched(self):
        tech = RecordingTech()
        led = TenantLedger({
            "bursty": TenantQuota(max_inflight=2, retry_after_s=0.7),
            "quiet": TenantQuota(max_inflight=8),
        })
        svc = _service(tech, start=False, tenancy=led)
        gw = GatewayServer(svc)
        _submit_frame(gw, "b-0", tenant="bursty")
        _submit_frame(gw, "b-1", tenant="bursty")
        with pytest.raises(GatewayError) as ei:
            _submit_frame(gw, "b-2", tenant="bursty")
        assert ei.value.code == protocol.GW_TENANT_OVER_QUOTA
        assert ei.value.retriable
        assert ei.value.retry_after_s == 0.7  # the tenant's own hint
        # The bursty tenant's refusal cost the quiet tenant nothing.
        for i in range(3):
            _submit_frame(gw, f"q-{i}", tenant="quiet")
        assert svc.queue.live_tenant("quiet") == 3
        assert led.snapshot()["bursty"]["shed"] == 1
        assert "quiet" not in {
            t for t, row in led.snapshot().items() if row["shed"]
        }
        assert gw.stats()["sheds"] == {"tenant_over_quota": 1}

    def test_pressure_shrink_targets_only_over_share_tenants(self):
        tech = RecordingTech()
        led = TenantLedger({
            "hog": TenantQuota(max_inflight=4),
            "quiet": TenantQuota(max_inflight=4),
        })
        svc = _service(tech, start=False, tenancy=led)
        gw = GatewayServer(svc, max_inflight=16)
        for i in range(3):
            _submit_frame(gw, f"h-{i}", tenant="hog")
        _submit_frame(gw, "q-0", tenant="quiet")
        # Simulate the deadline-pressure shedder having just evicted.
        svc.last_pressure_shed = time.monotonic()
        # hog is over its fair share (3 of 4 live): its window shrinks
        # 4 -> 2, and at 3 live it sheds.
        with pytest.raises(GatewayError) as ei:
            _submit_frame(gw, "h-3", tenant="hog")
        assert ei.value.code == protocol.GW_TENANT_OVER_QUOTA
        assert "pressure-shrunk" in ei.value.message
        # quiet keeps its full window — pressure didn't touch it.
        _submit_frame(gw, "q-1", tenant="quiet")
        assert svc.queue.live_tenant("quiet") == 2

    def test_non_string_tenant_refused(self):
        tech = RecordingTech()
        svc = _service(tech, start=False, tenancy=TenantLedger())
        gw = GatewayServer(svc)
        with pytest.raises(GatewayError) as ei:
            gw._op_submit(
                {"op": "submit",
                 "job": {"name": "x", "total_batches": 3, "spec": SPEC,
                         "tenant": 123}},
                "s", time.monotonic(),
            )
        assert ei.value.code == protocol.GW_BADREQUEST


# -------------------------------------------------------- replicated gateways
class TestReplicatedGateways:
    def _pair(self, svc, ttl_s=30.0):
        lease = ReplicaLease(ttl_s=ttl_s)
        gw_a = GatewayServer(svc, replica_id="gw-a", lease=lease)
        gw_b = GatewayServer(svc, replica_id="gw-b", replica_of=gw_a)
        return lease, gw_a, gw_b

    def test_replica_must_front_same_service(self):
        tech = RecordingTech()
        svc1 = _service(tech, start=False)
        svc2 = _service(tech, start=False)
        gw = GatewayServer(svc1, replica_id="gw-a")
        with pytest.raises(ValueError):
            GatewayServer(svc2, replica_of=gw)

    def test_non_leaseholder_refuses_retriable_but_serves_dedup(self):
        tech = RecordingTech()
        svc = _service(tech, start=False)
        lease, gw_a, gw_b = self._pair(svc)
        out = _submit_frame(gw_a, "r-0", dedup_key="k-r0")
        assert not out["duplicate"] and lease.owner == "gw-a"
        # A fresh submit against the non-holder is refused retriably...
        with pytest.raises(GatewayError) as ei:
            _submit_frame(gw_b, "r-1", dedup_key="k-r1")
        assert ei.value.code == protocol.GW_RETRY_AFTER
        assert "gw-a" in ei.value.message
        # ...but a retried lost-ACK is served from the shared dedup
        # table by ANY replica, lease-free: the answer is already durable.
        dup = _submit_frame(gw_b, "r-0", dedup_key="k-r0")
        assert dup == {"job_id": out["job_id"], "duplicate": True}
        assert svc.queue.live() == 1

    def test_clean_shutdown_hands_lease_to_peer(self):
        tech = RecordingTech()
        svc = _service(tech, start=False)
        lease, gw_a, gw_b = self._pair(svc)
        _submit_frame(gw_a, "h-0")
        assert lease.epoch == 1
        gw_a.shutdown(timeout=2.0)
        _submit_frame(gw_b, "h-1")
        assert lease.owner == "gw-b" and lease.epoch == 2

    def test_stale_epoch_fenced_nothing_admitted(self, monkeypatch):
        tech = RecordingTech()
        svc = _service(tech, start=False)
        lease, gw_a, gw_b = self._pair(svc)
        _submit_frame(gw_a, "f-0")
        stale = lease.epoch
        # Depose gw-a: the failure detector declares it dead, gw-b takes
        # over with a bumped epoch.
        lease.mark_dead("gw-a")
        _submit_frame(gw_b, "f-1")
        assert lease.epoch == stale + 1
        # gw-a's late request arrives still holding the fenced epoch
        # (the deposal happened between its lease check and its commit).
        monkeypatch.setattr(gw_a, "_ensure_lease", lambda session: stale)
        live_before = svc.queue.live()
        with pytest.raises(GatewayError) as ei:
            _submit_frame(gw_a, "f-2", dedup_key="k-stale")
        assert ei.value.code == protocol.GW_STALE_EPOCH
        assert ei.value.retriable
        # The fence fired BEFORE anything was admitted or recorded.
        assert svc.queue.live() == live_before
        assert "k-stale" not in gw_a._dedup
        assert gw_a.stats()["sheds"].get("stale_epoch") == 1


# --------------------------------------------------- compile-ahead in service
class TestServiceCompileAhead:
    def test_admit_prewarms_and_journals(self, tmp_path):
        wal = str(tmp_path / "wal")
        tech = RecordingTech()
        pool = CompileAheadPool(workers=1)
        svc = _service(tech, wal=wal, compile_ahead=pool)
        try:
            rec = svc.queue.submit(JobRequest(
                task=PrewarmTask("pw-0", 3, SPEC["sizes"], tech),
                spec=SPEC,
            ))
            assert svc.queue.wait(rec.job_id, timeout=30).state.value \
                == "DONE"
            assert pool.wait_idle(timeout=5.0)
            assert pool.acquire("ca-pw-0") == "exe-pw-0"
            led = pool.ledger()
            assert led["requested"] == 1 and led["ready"] == 1
            assert led["hit_rate"] == 1.0
        finally:
            svc.stop(timeout=30)
        # The lifecycle is durable: requested/ready/hit all journaled.
        state = replay_service_state(wal)
        assert state.compile_ahead.get("requested") == 1
        assert state.compile_ahead.get("ready") == 1
        assert state.compile_ahead.get("hit") == 1


# ------------------------------------------------------- kill/replay tenancy
class TestKillReplay:
    def test_charges_and_lease_epoch_survive_kill_replay(self, tmp_path):
        wal = str(tmp_path / "wal")
        tech = RecordingTech()

        # --- phase A: a completed job charges its tenant ----------------
        led = TenantLedger()
        svc = _service(tech, wal=wal, tenancy=led)
        lease = ReplicaLease(ttl_s=30.0)
        gw = GatewayServer(svc, replica_id="gw-a", lease=lease).start()
        try:
            with GatewayClient(*gw.address, seed=3, timeout_s=5.0) as c:
                jid1 = c.submit(name="kr-one", total_batches=3, spec=SPEC,
                                tenant="acme", dedup_key="k-kr1")
                assert c.wait(jid1, timeout=60)["state"] == "DONE"
        finally:
            gw.shutdown(timeout=5.0)
            svc.stop(timeout=60)
        charged_a = led.charged("acme")
        assert charged_a > 0

        # --- phase B: restart, then a kill mid-ACK ----------------------
        # No service loop this incarnation: the submit path needs only
        # queue+journal, and an idle loop would race the injector for
        # barrier crossings.
        inj = CrashInjector("post-commit", hit=1, armed=False)
        led2 = TenantLedger()
        svc2 = _service(tech, wal=wal, barrier=inj.barrier, start=False,
                        tenancy=led2)
        # Recovery re-seats the quota ledger from the journal fold.
        assert led2.charged("acme") == pytest.approx(charged_a, rel=1e-6)
        assert svc2.recovered_lease_epoch == 1
        assert svc2.recovered_lease_owner == "gw-a"
        lease2 = ReplicaLease(ttl_s=30.0, epoch=svc2.recovered_lease_epoch)
        gw2 = GatewayServer(svc2, replica_id="gw-b", lease=lease2).start()
        # Take the lease BEFORE arming: the takeover journals (and commits)
        # a gateway_lease record, which would otherwise absorb the one
        # armed post-commit kill meant for the job admission.
        assert lease2.ensure("gw-b") == 2
        inj.arm()
        with pytest.raises(GatewayError) as ei:
            GatewayClient(*gw2.address, session="killer", seed=13,
                          max_attempts=2, timeout_s=2.0,
                          backoff_base_s=0.01).submit(
                name="kr-two", total_batches=3, spec=SPEC, tenant="acme",
                dedup_key="k-kr2")
        assert ei.value.code == protocol.GW_UNAVAILABLE
        assert inj.fired.is_set() and gw2.killed
        state = replay_service_state(wal)
        # The admission (and gw-b's lease acquisition) were durable
        # before the kill point; the charges did not double.
        original = state.dedup["k-kr2"]
        assert state.lease_epoch == 2 and state.lease_owner == "gw-b"
        assert state.tenant_charges["acme"] == pytest.approx(
            charged_a, rel=1e-6)

        # --- phase C: recover, retry the lost ACK, finish the job -------
        led3 = TenantLedger()
        svc3 = _service(tech, wal=wal, tenancy=led3)
        assert svc3.recovered_lease_epoch == 2
        lease3 = ReplicaLease(ttl_s=30.0, epoch=svc3.recovered_lease_epoch)
        gw3 = GatewayServer(svc3, replica_id="gw-c", lease=lease3).start()
        try:
            with GatewayClient(*gw3.address, session="killer",
                               seed=13) as c3:
                # Same dedup key against the new replica: original job
                # id, no re-admission.
                jid2 = c3.submit(name="kr-two", total_batches=3, spec=SPEC,
                                 tenant="acme", dedup_key="k-kr2")
                assert jid2 == original
                # Serving the retry is lease-free (dedup-before-lease):
                # gw-c answered from the shared table without taking the
                # lease, so the epoch has NOT advanced yet.
                assert lease3.epoch == 2
                assert c3.wait(jid2, timeout=60)["state"] == "DONE"
                # A fresh admission DOES need the lease: gw-c's takeover
                # continues the epoch sequence past every fenced one.
                jid3 = c3.submit(name="kr-three", total_batches=3,
                                 spec=SPEC, tenant="acme")
                assert c3.wait(jid3, timeout=60)["state"] == "DONE"
            assert lease3.epoch == 3 and lease3.owner == "gw-c"
        finally:
            gw3.shutdown(timeout=5.0)
            svc3.stop(timeout=60)
        final = replay_service_state(wal)
        names = sorted(j.task for j in final.jobs.values())
        assert names == ["kr-one", "kr-three", "kr-two"]  # zero duplicates
        epochs = [e for e, _, _ in final.lease_history]
        assert sorted(epochs) == [1, 2, 3]  # minted exactly once each
        # Charges folded exactly-once across all three incarnations:
        # kr-one's from phase A plus kr-two's and kr-three's from
        # phase C, no doubling.
        assert final.tenant_charges["acme"] == pytest.approx(
            led3.charged("acme"), rel=1e-6)
        assert final.tenant_charges["acme"] > charged_a

    def test_tenancy_cli_summarizes_journal(self, tmp_path, capsys):
        wal = str(tmp_path / "wal")
        tech = RecordingTech()
        led = TenantLedger()
        svc = _service(tech, wal=wal, tenancy=led)
        lease = ReplicaLease(ttl_s=30.0)
        gw = GatewayServer(svc, replica_id="gw-a", lease=lease).start()
        try:
            with GatewayClient(*gw.address, seed=5, timeout_s=5.0) as c:
                for i, tenant in enumerate(["acme", "acme", "zeta"]):
                    jid = c.submit(name=f"cli-{i}", total_batches=3,
                                   spec=SPEC, tenant=tenant)
                    assert c.wait(jid, timeout=60)["state"] == "DONE"
        finally:
            gw.shutdown(timeout=5.0)
            svc.stop(timeout=60)
        rc = cli_main(["--json", "tenancy", wal])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["fencing_violations"] == []
        assert payload["lease"]["current_epoch"] == 1
        assert payload["lease"]["current_owner"] == "gw-a"
        assert payload["tenants"]["acme"]["submitted"] == 2
        assert payload["tenants"]["acme"]["admit"] == 2
        assert payload["tenants"]["zeta"]["submitted"] == 1
        assert payload["tenants"]["acme"]["charged_chip_s"] > 0


# -------------------------------------------- replica failover acceptance
def _trajectory(wal):
    state = replay_service_state(wal)
    out = {}
    for j in state.jobs.values():
        assert j.task not in out, f"duplicate admission for {j.task}"
        out[j.task] = (j.state, j.realized, j.total_batches)
    return out


@pytest.mark.slow
@pytest.mark.parametrize("seed", [13, 29])
def test_replica_killed_mid_ack_zero_lost_zero_dup(seed, tmp_path):
    """The acceptance campaign: two gateway replicas over one journal,
    the leaseholder's wire killed mid-ACK by seeded netchaos. Clients
    fail over to the peer and retry; the shared dedup table + lease
    fencing must yield zero lost jobs, zero duplicate admissions, and a
    strictly-once epoch sequence — then a clean failover hands the lease
    to the survivor."""
    wal = str(tmp_path / "wal")
    tech = RecordingTech()
    led = TenantLedger()
    svc = _service(tech, wal=wal, tenancy=led)
    lease = ReplicaLease(ttl_s=1.0)
    gw_a = GatewayServer(svc, replica_id="gw-a", lease=lease).start()
    gw_b = GatewayServer(svc, replica_id="gw-b", replica_of=gw_a).start()
    spec = single_fault_spec(seed, "kill_ack", fault_rate=0.4,
                             max_faults_per_conn=2)
    mix = [(f"fo-{seed}-{i}", 3 + (i % 3),
            "acme" if i % 2 else "zeta") for i in range(6)]
    try:
        with NetChaosProxy(*gw_a.address, spec) as px:
            with GatewayClient(*px.address, seed=seed, timeout_s=5.0,
                               max_attempts=10,
                               endpoints=[gw_b.address]) as c:
                ids = [c.submit(name=name, total_batches=total, spec=SPEC,
                                tenant=tenant)
                       for name, total, tenant in mix]
                for jid in ids:
                    assert c.wait(jid, timeout=90)["state"] == "DONE", jid
            injected = dict(px.stats.injected)
        assert injected.get("kill_ack", 0) > 0, \
            "campaign never exercised a mid-ACK kill"
        # Phase 2: the leaseholder drains away; the peer takes over with
        # a bumped epoch and keeps admitting.
        gw_a.shutdown(timeout=10.0, reason="failover")
        with GatewayClient(*gw_b.address, seed=seed + 1, timeout_s=5.0,
                           max_attempts=10) as c2:
            for i in range(2):
                jid = c2.submit(name=f"fo2-{seed}-{i}", total_batches=3,
                                spec=SPEC, tenant="acme")
                assert c2.wait(jid, timeout=90)["state"] == "DONE"
        assert lease.owner == "gw-b" and lease.epoch == 2
    finally:
        gw_b.shutdown(timeout=10.0, reason="campaign")
        svc.stop(timeout=60)

    traj = _trajectory(wal)  # asserts zero duplicate admissions
    expected = {name for name, _, _ in mix} | {
        f"fo2-{seed}-{i}" for i in range(2)
    }
    assert set(traj) == expected, "lost or phantom jobs"
    assert all(st == "DONE" and r >= t for st, r, t in traj.values())
    state = replay_service_state(wal)
    assert state.lease_epoch == 2 and state.lease_owner == "gw-b"
    epochs = [e for e, _, _ in state.lease_history]
    assert len(epochs) == len(set(epochs)), "fenced epoch reused"
    # Every admission is tenant-tagged in the durable record.
    assert state.tenant_charges.keys() >= {"acme", "zeta"}
