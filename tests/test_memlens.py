"""Unit tests for the memlens liveness model and SAT-M pass plumbing.

The differential oracle against ``compiled.memory_analysis()`` lives in
``test_memlens_differential.py``; these tests pin the *model semantics* on
toy jaxprs (donation frees, scan carries persist, remat bodies are
transient-only, windows stack batch shards) and the pass contracts
(sanctions downgrade, capacity resolution, verdicts fail open).
"""

import pytest

import jax
import jax.numpy as jnp

from saturn_tpu.analysis.memlens import liveness
from saturn_tpu.analysis.memlens import passes as ml_passes
from saturn_tpu.analysis.shardflow.interp import _replicated

pytestmark = pytest.mark.analysis

MB = 1 << 20
N = 512  # 512x512 f32 = 1 MiB per buffer


def _closed(fn, *args):
    return jax.make_jaxpr(fn)(*args)


def _analyze(closed, donated, **kw):
    jaxpr = closed.jaxpr
    in_specs = [_replicated(v.aval) for v in jaxpr.invars]
    return liveness.analyze_closed(closed, in_specs, {}, donated=donated, **kw)


def _x():
    return jnp.zeros((N, N), jnp.float32)


# ----------------------------------------------------------------- liveness
def test_donation_reduces_simulated_peak():
    def f(x, y):
        z = x * 2.0
        return z + y

    closed = _closed(f, _x(), _x())
    plain = _analyze(closed, donated=[False, False])
    donated = _analyze(closed, donated=[True, True])
    assert donated.peak_bytes < plain.peak_bytes
    assert donated.donated_bytes == 2 * MB
    # donation releases x at its last read: one fewer buffer at the peak
    assert plain.peak_bytes - donated.peak_bytes == MB


def test_missed_donation_flagged_only_when_undonated():
    def f(x, y):
        z = x * 2.0
        return z + y

    closed = _closed(f, _x(), _x())
    plain = _analyze(closed, donated=[False, False])
    # both inputs match the output's shape/dtype and neither is donated
    assert len(plain.missed_donations) == 2
    assert plain.missed_donations[0]["bytes"] == MB
    donated = _analyze(closed, donated=[True, True])
    assert donated.missed_donations == []


def test_scan_carry_persists_across_iterations():
    def f(c, xs):
        def body(c, x):
            t = c * 2.0
            return t + x, t

        return jax.lax.scan(body, c, xs)

    xs = jnp.zeros((4, N, N), jnp.float32)
    prof = _analyze(_closed(f, _x(), xs), donated=[False, False])
    # carry + the full stacked xs/ys must be resident; body temps from all
    # 4 iterations must NOT stack up (one-iteration residency)
    assert prof.peak_bytes >= 9 * MB  # c + xs(4) + ys(4)
    assert prof.peak_bytes <= 13 * MB


def test_remat_body_is_transient_only():
    def g(x):
        a = x * 2.0
        b = a + 1.0
        c = b * 3.0
        return c.sum()

    def plain(x):
        return g(x) + 1.0

    def rematted(x):
        return jax.checkpoint(g)(x) + 1.0

    p_plain = _analyze(_closed(plain, _x()), donated=[False])
    p_remat = _analyze(_closed(rematted, _x()), donated=[False])
    # the remat frame force-frees its residuals on exit, so its peak can
    # never exceed the inline version's
    assert p_remat.peak_bytes <= p_plain.peak_bytes
    assert p_remat.peak_bytes >= MB  # the input itself stays live


def test_per_shard_bytes_divides_by_mesh_axes():
    aval = jax.ShapeDtypeStruct((N, N), jnp.float32)
    full = liveness.per_shard_bytes(aval, ((), ()), {"dp": 4})
    sharded = liveness.per_shard_bytes(aval, (("dp",), ()), {"dp": 4})
    assert full == MB
    assert sharded == MB // 4


# ---------------------------------------------------------------- sanctions
def test_sanction_marker_on_line_and_comment_block():
    lines = [
        "x = 1",
        "# sanctioned-memlens: audited 2026-08",
        "y = big_gather(x)",
        "z = y + 1",
    ]
    assert ml_passes._sanction_in_lines(lines, 3) == "audited 2026-08"
    assert ml_passes._sanction_in_lines(lines, 2) == "audited 2026-08"
    assert ml_passes._sanction_in_lines(lines, 4) is None


def test_sanction_at_resolves_file_line(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text("# sanctioned-memlens: fits with offload\nval = f()\n")
    assert ml_passes._sanction_at(f"{src}:2") == "fits with offload"
    assert ml_passes._sanction_at(f"{src}:1") == "fits with offload"
    assert ml_passes._sanction_at("eqn#7(dot_general)") is None
    assert ml_passes._sanction_at("") is None


# ----------------------------------------------------------------- capacity
def test_hbm_capacity_env_precedence(monkeypatch):
    monkeypatch.setenv(ml_passes.ENV_CAPACITY, str(16 * 1024**3))
    assert ml_passes.hbm_capacity_bytes() == 16 * 1024**3
    monkeypatch.setenv(ml_passes.ENV_CAPACITY, "not-a-number")
    assert ml_passes.hbm_capacity_bytes() == 0
    monkeypatch.delenv(ml_passes.ENV_CAPACITY)
    assert ml_passes.hbm_capacity_bytes() == 0  # no devices, no env


def test_audit_point_fires_both_directions():
    assert ml_passes.audit_point(300, 100, "dp", 4) is not None
    assert ml_passes.audit_point(100, 300, "dp", 4) is not None
    assert ml_passes.audit_point(100, 120, "dp", 4) is None
    assert ml_passes.audit_point(0, 100, "dp", 4) is None
    assert ml_passes.audit_point(100, 0, "dp", 4) is None
    d = ml_passes.audit_point(1000, 100, "tp", 8, k=2)
    assert d.code == "SAT-M005" and d.severity == "warning"


# ------------------------------------------------- traced-technique behavior
@pytest.fixture()
def dp_traced(tiny_task, devices8):
    from saturn_tpu import library as lib

    if not lib.registered_names():
        lib.register_default_library()
    cls = lib.retrieve("dp")
    tech = cls() if isinstance(cls, type) else cls
    config = tech.candidate_configs(tiny_task, 4)[0]
    return tech, tech.trace_step(tiny_task, devices8[:4], config)


def test_window_adds_one_batch_shard_per_extra_step(dp_traced):
    _, traced = dp_traced
    shard = liveness.per_shard_bytes(
        traced["batch_sds"],
        liveness._from_pspec(traced["batch_spec"],
                             len(traced["batch_sds"].shape)),
        dict(traced["mesh_axes"]),
    )
    assert shard > 0
    p2 = liveness.analyze(traced, window=2)
    p3 = liveness.analyze(traced, window=3)
    assert p3.peak_bytes - p2.peak_bytes == shard


def test_sat_m001_deterministic_under_small_capacity(dp_traced):
    _, traced = dp_traced
    report, profile = ml_passes.analyze_traced(traced, capacity_bytes=1024)
    assert profile.peak_bytes > 1024
    assert any(d.code == "SAT-M001" and d.severity == "error"
               for d in report.diagnostics)
    report2, _ = ml_passes.analyze_traced(traced, capacity_bytes=1 << 60)
    assert not any(d.code == "SAT-M001" for d in report2.diagnostics)


def test_grid_point_infeasible_is_conservative(dp_traced, tiny_task, devices8):
    tech, _ = dp_traced
    devices = devices8[:4]
    # unknown capacity: never prunes
    assert not ml_passes.grid_point_infeasible(tech, tiny_task, devices, 0)
    # generous capacity: fits, never prunes
    assert not ml_passes.grid_point_infeasible(
        tech, tiny_task, devices, 1 << 60)
    # absurdly small capacity: every config predicts OOM -> prune
    assert ml_passes.grid_point_infeasible(tech, tiny_task, devices, 1024)

    class NoTrace:
        name = "opaque"

    # a technique without trace_step can never be pruned statically
    assert not ml_passes.grid_point_infeasible(
        NoTrace(), tiny_task, devices, 1024)


def test_prediction_feeds_fits_compiled_calibration(dp_traced, tiny_task,
                                                    devices8, tmp_path):
    """_fits_memory's calibration hook emits predicted-vs-compiled bytes."""
    import json

    from jax.sharding import NamedSharding, PartitionSpec

    from saturn_tpu.core.mesh import make_submesh
    from saturn_tpu.utils import metrics

    tech, traced = dp_traced
    config = tech.candidate_configs(tiny_task, 4)[0]
    axis_names, axis_sizes = tech.mesh_spec(4, tiny_task, config)
    mesh = make_submesh(devices8[:4], axis_names, axis_sizes)
    spec = tiny_task.get_model()
    ds = tiny_task.get_dataset()
    _, train_step = tech.make_step_fns(spec, tiny_task, config, mesh, ds)
    state_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s if s is not None else PartitionSpec()),
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    compiled = (
        jax.jit(train_step,
                in_shardings=(state_sh, NamedSharding(mesh,
                                                      traced["batch_spec"])),
                donate_argnums=(0,))
        .lower(traced["state_shapes"], traced["batch_sds"])
        .compile()
    )
    path = str(tmp_path / "metrics.jsonl")
    with metrics.scoped(path):
        assert tech._fits_compiled(compiled, devices8[:4], task=tiny_task,
                                   config=config, k=1)
    events = [json.loads(l) for l in open(path) if l.strip()]
    cal = [e for e in events if e.get("kind") == "memlens_calibration"]
    assert len(cal) == 1
    assert cal[0]["technique"] == "dp" and cal[0]["k"] == 1
    assert cal[0]["predicted_bytes"] > 0
    assert cal[0]["compiled_bytes"] >= 0


# -------------------------------------------------------------- env margins
def test_prune_margin_env_default():
    assert ml_passes.OOM_MARGIN >= 1.0  # never prune inside capacity
    assert 0.0 < ml_passes.HEADROOM_MARGIN < 1.0


def test_env_hbm_bytes_backstop(monkeypatch):
    from saturn_tpu.parallel import spmd_base

    monkeypatch.delenv(ml_passes.ENV_CAPACITY, raising=False)
    assert spmd_base._env_hbm_bytes() == 0
    monkeypatch.setenv(ml_passes.ENV_CAPACITY, "123456")
    assert spmd_base._env_hbm_bytes() == 123456
    monkeypatch.setenv(ml_passes.ENV_CAPACITY, "junk")
    assert spmd_base._env_hbm_bytes() == 0


# ------------------------------------------------- pipeline stash residency
class TestPipelineStashResidency:
    """Round 20 (SAT-M regression): the staged pipeline's activation stash.

    1F1B's whole memory claim is that the stash ring is ``min(M, 2S-1)``
    deep — O(S), independent of the microbatch count — while the GPipe
    ordering keeps all ``M`` in-flight inputs resident. The analytic model
    (``ml_passes.pipeline_stash_bytes``) pins the formula; the traced check
    holds the generic scan-carry liveness rule to the same delta, so a
    liveness change that stops seeing the stash (or a schedule change that
    silently grows it) breaks here before it mis-prices feasibility.
    """

    def test_analytic_model_bounds(self):
        unit = 1024
        S = 4
        # 1F1B plateaus at 2S-1 = 7 stashed microbatches...
        assert ml_passes.pipeline_stash_bytes("1f1b", S, 2, unit) == 2 * unit
        assert ml_passes.pipeline_stash_bytes("1f1b", S, 8, unit) == 7 * unit
        assert ml_passes.pipeline_stash_bytes("1f1b", S, 64, unit) == 7 * unit
        # ...the GPipe ordering grows linearly in M
        assert ml_passes.pipeline_stash_bytes("gpipe", S, 8, unit) == 8 * unit
        assert (ml_passes.pipeline_stash_bytes("gpipe", S, 64, unit)
                == 64 * unit)
        for m in (2, 4, 8, 64):
            assert (ml_passes.pipeline_stash_bytes("1f1b", S, m, unit)
                    <= ml_passes.pipeline_stash_bytes("gpipe", S, m, unit))

    def test_analytic_model_matches_ops_depth(self):
        from saturn_tpu.ops.pipeline import stash_depth

        for sched in ("1f1b", "gpipe"):
            for s in (2, 4):
                for m in (2, 7, 16):
                    assert (ml_passes.pipeline_stash_bytes(sched, s, m, 3)
                            == 3 * stash_depth(s, m, sched))

    def test_traced_liveness_sees_the_stash_delta(self):
        """At equal per-microbatch size, the traced peak gap between the two
        staged schedules tracks the analytic stash delta (within the carry
        in/out double-residency factor of the liveness model)."""
        import numpy as np
        from jax.sharding import Mesh

        from saturn_tpu.ops.pipeline import staged_pipeline_loss_and_grads

        L, DM, V, T = 4, 16, 31, 12
        key = jax.random.PRNGKey(0)
        k1, k2, k3, k4 = jax.random.split(key, 4)
        params = {
            "emb": jax.random.normal(k1, (V, DM)) * 0.02,
            "blocks": {
                "w": jax.random.normal(k2, (L, DM, DM)) * 0.1,
                "b": jnp.zeros((L, DM)),
            },
            "head": jax.random.normal(k3, (DM, V)) * 0.02,
        }
        d, S, M, B = 2, 4, 14, 56
        devs = np.array(jax.devices()[:8]).reshape(d, S)
        mesh = Mesh(devs, ("data", "stage"))
        fns = dict(
            mesh=mesh, block_key="blocks",
            embed_fn=lambda o, t: o["emb"][t],
            block_fn=lambda lp, h: jnp.tanh(h @ lp["w"] + lp["b"]),
            head_fn=lambda o, h: h @ o["head"],
            loss_fn=lambda lg, t: -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(lg, axis=-1), t[..., None], axis=-1)),
        )
        tokens = jax.random.randint(k4, (B, T), 0, V)

        def peak(schedule):
            closed = jax.make_jaxpr(
                lambda p, t: staged_pipeline_loss_and_grads(
                    p, t, n_microbatches=M, schedule=schedule, **fns)
            )(params, tokens)
            in_specs = [_replicated(v.aval) for v in closed.jaxpr.invars]
            return liveness.analyze_closed(closed, in_specs, {}).peak_bytes

        gap = peak("gpipe") - peak("1f1b")
        assert gap > 0, "1f1b must be the smaller traced peak at M > 2S-1"
        # per-(stage, data)-shard stage-input microbatch: (B/d/M, T, DM) f32
        unit = (B // d // M) * T * DM * 4
        analytic = (ml_passes.pipeline_stash_bytes("gpipe", S, M, unit)
                    - ml_passes.pipeline_stash_bytes("1f1b", S, M, unit))
        assert 0.5 * analytic <= gap <= 4.0 * analytic, (gap, analytic)
