"""saturn-tsan tests: static SAT-C fixtures, the runtime sanitizer, and
seeded deterministic interleavings of the real queue/journal hot paths."""

from __future__ import annotations

import json
import sys
import threading
import types

import pytest

pytestmark = pytest.mark.concurrency

from saturn_tpu.analysis.concurrency import sanitizer
from saturn_tpu.analysis.concurrency import static_pass
from saturn_tpu.analysis.concurrency.interleave import (
    InterleaveScheduler,
    sched_point,
)


@pytest.fixture(autouse=True)
def _clean_tracing():
    """Every test starts and ends with tracing off and an empty recorder."""
    sanitizer.set_active(False)
    sanitizer.recorder().reset()
    yield
    sanitizer.set_active(False)
    sanitizer.recorder().reset()


def _analyze_src(tmp_path, name: str, src: str):
    p = tmp_path / name
    p.write_text(src)
    return static_pass.analyze_paths([str(p)])


def _codes(report, severity=None):
    return sorted(
        d.code for d in report.diagnostics
        if severity is None or d.severity == severity
    )


# ---------------------------------------------------------------------------
# static pass: per-code toy fixtures
# ---------------------------------------------------------------------------


class TestStaticPassFixtures:
    def test_c001_lock_order_inversion(self, tmp_path):
        report = _analyze_src(tmp_path, "inv.py", """
import threading
A = threading.Lock()
B = threading.Lock()

def left():
    with A:
        with B:
            pass

def right():
    with B:
        with A:
            pass
""")
        errs = [d for d in report.errors if d.code == "SAT-C001"]
        assert errs, report.render()
        cyc = errs[0].counterexample["cycle"]
        assert cyc[0] == cyc[-1] and len(set(cyc)) == 2
        # every edge of the counterexample carries a file:line witness
        assert all(e["where"].endswith(tuple("0123456789"))
                   for e in errs[0].counterexample["edges"])

    def test_c001_consistent_order_is_clean(self, tmp_path):
        report = _analyze_src(tmp_path, "ok.py", """
import threading
A = threading.Lock()
B = threading.Lock()

def left():
    with A:
        with B:
            pass

def right():
    with A:
        with B:
            pass
""")
        assert not [d for d in report.errors if d.code == "SAT-C001"]

    def test_c001_self_deadlock_on_plain_lock(self, tmp_path):
        report = _analyze_src(tmp_path, "self.py", """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner_direct()

    def inner_direct(self):
        with self._lock:
            pass
""")
        # outer holds the non-reentrant lock while inner re-acquires it:
        # inner's effective lock-context makes this a self-deadlock
        assert "SAT-C001" in _codes(report, "error"), report.render()

    def test_c001_rlock_reentry_is_clean(self, tmp_path):
        report = _analyze_src(tmp_path, "re.py", """
import threading

class Box:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
""")
        assert not report.errors, report.render()

    def test_c002_inconsistent_attr_guard(self, tmp_path):
        report = _analyze_src(tmp_path, "attr.py", """
import threading

class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}

    def guarded(self, k):
        with self._lock:
            self._counts[k] = self._counts.get(k, 0) + 1

    def unguarded(self, k):
        self._counts[k] = 0
""")
        errs = [d for d in report.errors if d.code == "SAT-C002"]
        assert errs, report.render()
        assert errs[0].counterexample["name"] == "_counts"

    def test_c002_sanction_downgrades_to_info(self, tmp_path):
        report = _analyze_src(tmp_path, "attr_ok.py", """
import threading

class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}

    def guarded(self, k):
        with self._lock:
            self._counts[k] = self._counts.get(k, 0) + 1

    def unguarded(self, k):
        # sanctioned-unlocked: single-writer path, audited
        self._counts[k] = 0
""")
        assert report.ok
        infos = [d for d in report.diagnostics
                 if d.code == "SAT-C002" and d.severity == "info"]
        assert infos and "audited" in infos[0].message

    def test_c002_thread_root_closure(self, tmp_path):
        report = _analyze_src(tmp_path, "closure.py", """
import threading

def run():
    results = {}

    def worker():
        results["a"] = 1

    def other():
        results["b"] = 2

    t = threading.Thread(target=worker)
    t.start()
    other()
""")
        errs = [d for d in report.errors if d.code == "SAT-C002"]
        assert errs, report.render()

    def test_c002_lock_managed_global(self, tmp_path):
        report = _analyze_src(tmp_path, "glob.py", """
import threading
_MU = threading.Lock()
_STATE = None

def set_state(v):
    global _STATE
    with _MU:
        _STATE = v

def get_state():
    return _STATE
""")
        errs = [d for d in report.errors if d.code == "SAT-C002"]
        assert errs and errs[0].counterexample["name"] == "_STATE"

    def test_c003_blocking_under_lock(self, tmp_path):
        report = _analyze_src(tmp_path, "blk.py", """
import os
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = open(__file__)

    def sync(self):
        with self._lock:
            os.fsync(self._fh.fileno())
""")
        errs = [d for d in report.errors if d.code == "SAT-C003"]
        assert errs and errs[0].counterexample["op"] == "fsync"

    def test_c003_function_level_sanction(self, tmp_path):
        report = _analyze_src(tmp_path, "blk_ok.py", """
import os
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = open(__file__)

    # sanctioned-unlocked: commit contract requires fsync under lock
    def sync(self):
        with self._lock:
            os.fsync(self._fh.fileno())

    def outer(self):
        with self._lock:
            self.sync()
""")
        # the function sanction both downgrades the direct fsync AND stops
        # may-block propagation into outer()'s call site
        assert report.ok, report.render()

    def test_c004_wait_without_loop(self, tmp_path):
        report = _analyze_src(tmp_path, "cond.py", """
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items = []

    def bad_wait(self):
        with self._cond:
            if not self._items:
                self._cond.wait()
            return self._items.pop()

    def good_wait(self):
        with self._cond:
            while not self._items:
                self._cond.wait()
            return self._items.pop()
""")
        errs = [d for d in report.errors if d.code == "SAT-C004"]
        assert len(errs) == 1
        assert "bad_wait" in errs[0].message

    def test_c000_unparsable_file(self, tmp_path):
        report = _analyze_src(tmp_path, "syn.py", "def broken(:\n")
        assert "SAT-C000" in _codes(report, "error")


# ---------------------------------------------------------------------------
# the audited thread mesh gates clean
# ---------------------------------------------------------------------------


class TestAuditedPackages:
    def test_zero_unsanctioned_findings(self):
        paths = static_pass.default_paths()
        assert paths, "run from the repo root"
        result = static_pass.run(paths)
        assert result.report.ok, result.report.render()

    def test_sanctioned_cases_stay_visible(self):
        report = static_pass.run(static_pass.default_paths()).report
        infos = [d for d in report.diagnostics if d.severity == "info"]
        # the audited sanctions: journal/metrics fsyncs, metrics._WRITER
        # reads, queue.wait_for_arrival's timed single wait
        assert any(d.code == "SAT-C003" for d in infos)
        assert any(d.code == "SAT-C004" for d in infos)
        assert all("[sanctioned:" in d.message for d in infos)


# ---------------------------------------------------------------------------
# deadlock demo: bad ordering caught statically AND at runtime; fix passes
# ---------------------------------------------------------------------------

_BAD_ORDER = """
import threading
A = threading.Lock()
B = threading.Lock()

def forward():
    with A:
        with B:
            pass

def backward():
    with B:
        with A:
            pass
"""

_GOOD_ORDER = _BAD_ORDER.replace(
    "def backward():\n    with B:\n        with A:",
    "def backward():\n    with A:\n        with B:",
)


class TestDeadlockDemo:
    def _drive(self, first_order, second_order, rendezvous):
        """Two threads acquire their two locks in the given orders. With
        ``rendezvous`` each takes its first lock, waits for the other, then
        tries the second with a timeout — the classic wedge. Returns
        (timed_out, runtime_cycles)."""
        sanitizer.set_active(True)
        try:
            a, b = sanitizer.lock("demo.A"), sanitizer.lock("demo.B")
        finally:
            sanitizer.set_active(False)
        locks = {"A": a, "B": b}
        gate = threading.Barrier(2, timeout=5.0)
        timed_out = []

        def actor(order):
            first, second = locks[order[0]], locks[order[1]]
            with first:
                if rendezvous:
                    gate.wait()
                if second.acquire(timeout=0.3):
                    second.release()
                else:
                    timed_out.append(order)
                if rendezvous:
                    # hold the first lock until both attempts resolve, so
                    # one thread's timeout can't hand its lock to the other
                    gate.wait()

        t1 = threading.Thread(target=actor, args=(first_order,))
        t2 = threading.Thread(target=actor, args=(second_order,))
        t1.start(); t2.start()
        t1.join(timeout=10); t2.join(timeout=10)
        assert not t1.is_alive() and not t2.is_alive()
        return timed_out, sanitizer.recorder().cycles()

    def test_inverted_order_deadlocks_and_both_layers_catch_it(self, tmp_path):
        # static: the toy module's graph has the A<->B cycle
        report = _analyze_src(tmp_path, "bad.py", _BAD_ORDER)
        assert "SAT-C001" in _codes(report, "error")
        # runtime: both threads wedge on the other's lock (the deadlock is
        # real — only the acquire timeout unwedges them) and the recorder's
        # observed-order graph closes the same cycle
        timed_out, cycles = self._drive("AB", "BA", rendezvous=True)
        assert len(timed_out) == 2
        assert cycles and sorted(set(cycles[0])) == ["demo.A", "demo.B"]

    def test_fixed_order_passes_both_layers(self, tmp_path):
        report = _analyze_src(tmp_path, "good.py", _GOOD_ORDER)
        assert not [d for d in report.errors if d.code == "SAT-C001"]
        timed_out, cycles = self._drive("AB", "AB", rendezvous=False)
        assert timed_out == [] and cycles == []

    def test_validate_against_merges_static_and_observed(self):
        # observed A->B plus a static B->A edge closes a cycle that neither
        # graph contains alone
        sanitizer.set_active(True)
        try:
            a, b = sanitizer.lock("val.A"), sanitizer.lock("val.B")
        finally:
            sanitizer.set_active(False)
        with a:
            with b:
                pass
        rec = sanitizer.recorder()
        assert rec.cycles() == []
        merged = rec.validate_against({("val.B", "val.A")})
        assert merged and sorted(set(merged[0])) == ["val.A", "val.B"]


# ---------------------------------------------------------------------------
# seeded interleavings of the real product hot paths
# ---------------------------------------------------------------------------


def _task(name):
    return types.SimpleNamespace(name=name)


def _queue_scenario(seed):
    """SubmissionQueue: submit/cancel racing the drain/mark service loop."""
    from saturn_tpu.service.queue import (
        JobRequest, JobState, SubmissionQueue,
    )

    with InterleaveScheduler(seed=seed, timeout=30.0) as sched:
        q = SubmissionQueue()
        drained = []

        def producer():
            for i in range(3):
                q.submit(JobRequest(_task(f"job{i}")))

        def canceller():
            # cancel whatever is registered at this instant (racing both
            # the producer's submits and the service drain); the explicit
            # point keeps this actor in the trace even when it runs first
            # and finds nothing to cancel
            sched_point("cancel.scan")
            for rec in q.jobs():
                q.cancel(rec.job_id)

        def service():
            for _ in range(4):
                q.wait_for_arrival(timeout=0.0)
                for rec in q.drain():
                    drained.append(rec.job_id)
                    if rec.state is JobState.QUEUED:
                        q.mark(rec, JobState.PROFILING)
                        q.mark(rec, JobState.SCHEDULED)

        sched.spawn(producer, name="producer")
        sched.spawn(canceller, name="canceller")
        sched.spawn(service, name="service")
        trace = sched.run()
    states = sorted(
        (r.job_id, r.state.value, r.cancel_requested) for r in q.jobs()
    )
    return trace, drained, states


def _journal_scenario(seed, root):
    """Journal: two appenders racing group-commit across a forced rotation."""
    from saturn_tpu.durability import journal as jmod

    with InterleaveScheduler(seed=seed, timeout=30.0) as sched:
        jnl = jmod.Journal(str(root), segment_max_bytes=256)

        def appender(tag):
            def f():
                for i in range(4):
                    jnl.append("tick", who=tag, i=i)
            return f

        def committer():
            for _ in range(5):
                jnl.commit()

        sched.spawn(appender("a"), name="app-a")
        sched.spawn(appender("b"), name="app-b")
        sched.spawn(committer, name="committer")
        trace = sched.run()
    jnl.commit()
    segments = jnl._segment_index
    jnl.close()
    records = [
        (r["seq"], r["kind"], r["data"].get("who"), r["data"].get("i"))
        for r in jmod.replay(str(root), strict=True)
    ]
    return trace, segments, records


class TestSeededInterleavings:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_queue_interleaving_deterministic(self, seed):
        first = _queue_scenario(seed)
        second = _queue_scenario(seed)
        assert first == second
        # the scheduler really interleaved: the trace has all three actors
        actors = {e.split("@")[0] for e in first[0]}
        assert actors == {"producer", "canceller", "service"}

    def test_queue_different_seeds_diverge(self):
        traces = {tuple(_queue_scenario(s)[0]) for s in (0, 1, 2)}
        assert len(traces) > 1

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_journal_interleaving_deterministic(self, seed, tmp_path):
        first = _journal_scenario(seed, tmp_path / "j1")
        second = _journal_scenario(seed, tmp_path / "j2")
        assert first == second
        trace, segments, records = first
        # rotation happened under race and strict replay holds: sequence
        # numbers are contiguous and every append survived
        assert segments > 1
        assert len([r for r in records if r[1] == "tick"]) == 8
        seqs = [r[0] for r in records]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_queue_to_journal_edge_recorded_and_validated(self, tmp_path):
        """The documented queue-lock -> journal-lock order (the observer
        hook the static pass cannot see) shows up at runtime and closes no
        cycle against the static graph."""
        from saturn_tpu.durability import journal as jmod
        from saturn_tpu.service.queue import JobRequest, SubmissionQueue

        sanitizer.set_active(True)
        try:
            jnl = jmod.Journal(str(tmp_path / "j"))
            q = SubmissionQueue(
                observer=lambda event, rec, **f: jnl.append(event, job=rec.job_id)
            )
        finally:
            sanitizer.set_active(False)
        q.submit(JobRequest(_task("observed")))
        jnl.close()
        rec = sanitizer.recorder()
        assert ("queue.lock", "journal.lock") in rec.edges()
        static = static_pass.run(static_pass.default_paths())
        assert rec.validate_against(static.order_pairs()) == []

    def test_guardian_ledgers_survive_contention(self):
        from saturn_tpu.health.guardian import (
            HungDispatchError, TrainingGuardian,
        )

        sanitizer.set_active(True)
        try:
            g = TrainingGuardian(journal=None)
        finally:
            sanitizer.set_active(False)
        errs = []

        def fault_loop(name):
            def f():
                try:
                    for i in range(50):
                        g.on_fault(
                            _task(name), HungDispatchError(name, 1.0, 2.0), i
                        )
                        g.benched(name, i + 100)
                        g.note_success(name)
                        g.detach(name)
                except BaseException as e:  # pragma: no cover
                    errs.append(e)
            return f

        threads = [
            threading.Thread(target=fault_loop(f"t{i}")) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs
        assert sanitizer.recorder().cycles() == []
        assert g.detached_names() == {"t0", "t1", "t2", "t3"}


# ---------------------------------------------------------------------------
# scheduler mechanics
# ---------------------------------------------------------------------------


class TestSchedulerMechanics:
    def test_nested_install_rejected(self):
        with InterleaveScheduler(seed=0):
            with pytest.raises(RuntimeError):
                InterleaveScheduler(seed=1).__enter__()

    def test_managed_thread_errors_surface(self):
        with InterleaveScheduler(seed=3) as sched:
            def boom():
                sched_point("pre")
                raise ValueError("boom")

            sched.spawn(boom, name="t")
            with pytest.raises(ValueError, match="boom"):
                sched.run()

    def test_unmanaged_threads_pass_through(self):
        with InterleaveScheduler(seed=0) as sched:
            hits = []

            def plain():
                sched_point("ignored")
                hits.append(1)

            t = threading.Thread(target=plain)
            t.start()
            t.join(timeout=5)
            assert hits == [1]
            assert sched.trace == []

    def test_points_while_locked_never_park(self):
        with InterleaveScheduler(seed=0) as sched:
            lk = sanitizer.lock("mech.L")

            def f():
                with lk:
                    sched_point("inside")

            sched.spawn(f, name="t")
            trace = sched.run()
        assert "t@inside+locked" in trace


# ---------------------------------------------------------------------------
# CLI + gating wiring
# ---------------------------------------------------------------------------


class TestCLI:
    def test_concurrency_subcommand_json(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(_BAD_ORDER)
        rc = main(["--json", "concurrency", str(bad)])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["by_code"]["SAT-C001"]["error"] >= 1
        assert out["order_edges"]
        assert out["ok"] is False

    def test_concurrency_subcommand_defaults_clean(self, capsys):
        from saturn_tpu.analysis.cli import main

        rc = main(["concurrency"])
        assert rc == 0
        assert "ok (0 error(s)" in capsys.readouterr().out

    def test_lint_session_includes_tsan_gate(self):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "lint_session", os.path.join(repo, "tools", "lint.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        src = open(os.path.join(repo, "tools", "lint.py")).read()
        assert "saturn-tsan" in src and "static_pass" in src


class TestBenchGuardRefusal:
    def test_env_instrumented_run_refused(self, monkeypatch, capsys):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_guard", os.path.join(repo, "benchmarks", "bench_guard.py")
        )
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)

        monkeypatch.setenv("SATURN_TPU_TSAN", "1")
        monkeypatch.setattr(bg, "latest_record", lambda: (1, {"value": 100.0}))
        monkeypatch.setattr(
            bg, "run_bench",
            lambda: (_ for _ in ()).throw(AssertionError("must not run")),
        )
        rc = bg.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 1 and out["status"] == "tsan_instrumented"

    def test_stamped_row_refused(self, monkeypatch, capsys):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_guard2", os.path.join(repo, "benchmarks", "bench_guard.py")
        )
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)

        monkeypatch.delenv("SATURN_TPU_TSAN", raising=False)
        monkeypatch.setattr(bg, "latest_record", lambda: (1, {"value": 100.0}))
        monkeypatch.setattr(
            bg, "run_bench", lambda: {"value": 120.0, "tsan": True},
        )
        rc = bg.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 1 and out["status"] == "tsan_instrumented"

    def test_tsan_reference_rows_never_baseline(self, monkeypatch, tmp_path):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_guard3", os.path.join(repo, "benchmarks", "bench_guard.py")
        )
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)

        (tmp_path / "BENCH_r1.json").write_text(json.dumps(
            {"parsed": {"value": 500.0, "tsan": True}}
        ))
        monkeypatch.setattr(bg, "REPO", str(tmp_path))
        assert bg.latest_record() is None


class TestTracedPrimitives:
    def test_factories_return_plain_types_when_off(self):
        import queue as queue_mod

        assert isinstance(sanitizer.lock("x"), type(threading.Lock()))
        assert isinstance(sanitizer.make_queue("x"), queue_mod.Queue)
        assert not isinstance(sanitizer.make_queue("x"), sanitizer.TracedQueue)

    def test_traced_queue_flags_indefinite_wait_under_lock(self):
        sanitizer.set_active(True)
        try:
            lk = sanitizer.lock("tq.L")
            tq = sanitizer.make_queue("tq.Q")
        finally:
            sanitizer.set_active(False)
        tq.put("x")
        with lk:
            tq.get()  # blocking get with no timeout, lock held
        assert "tq.L" in sanitizer.recorder().blocking_under_lock()

    def test_condition_wait_releases_held_stack(self):
        sanitizer.set_active(True)
        try:
            lk = sanitizer.lock("cv.L")
            cv = sanitizer.condition(lk, "cv.C")
        finally:
            sanitizer.set_active(False)
        seen = []

        def waiter():
            with cv:
                seen.append(sanitizer.held_locks())
                cv.wait(timeout=5)
                seen.append(sanitizer.held_locks())

        t = threading.Thread(target=waiter)
        t.start()
        deadline = 50
        while deadline and not seen:
            threading.Event().wait(0.02)
            deadline -= 1
        with cv:
            # waiter is blocked in wait(): its held stack was popped, so
            # this thread's acquisition recorded no ordering under cv.L
            cv.notify_all()
        t.join(timeout=5)
        assert seen[0] == ("cv.L",) and seen[1] == ("cv.L",)
        assert sanitizer.recorder().cycles() == []
