"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.ops.flash import flash_attention


def dense_attention(q, k, v, causal=True):
    B, H, T, D = q.shape
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def mk_qkv(B=2, H=2, T=128, D=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
        for _ in range(3)
    )


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = mk_qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_uneven_blocks(self):
        q, k, v = mk_qkv(T=192)
        out = flash_attention(q, k, v, block_q=64, block_k=32)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_rejects_indivisible(self):
        q, k, v = mk_qkv(T=100)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64)

    def test_bf16(self):
        q, k, v = (t.astype(jnp.bfloat16) for t in mk_qkv())
        out = flash_attention(q, k, v, block_q=64, block_k=64)
        ref = dense_attention(q, k, v)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
            rtol=2e-2, atol=2e-2,
        )


class TestFlashGQA:
    """Grouped-query attention: k/v carry KV < H heads; the kernels map
    each q head to its group row, and dk/dv return the in-kernel group sum
    — must match repeat-k/v + dense exactly (fwd and all three grads)."""

    @staticmethod
    def _mk(B=2, H=4, KV=2, T=128, D=16, seed=3):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, KV, T, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, KV, T, D)), jnp.float32)
        return q, k, v

    @staticmethod
    def _ref(q, k, v, causal=True):
        rep = q.shape[1] // k.shape[1]
        return dense_attention(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            causal=causal,
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_repeat_dense(self, causal):
        q, k, v = self._mk()
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        ref = self._ref(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_match_repeat_dense(self):
        q, k, v = self._mk()

        def flash_loss(q_, k_, v_):
            return jnp.sum(
                flash_attention(q_, k_, v_, block_q=64, block_k=64) ** 2
            )

        def ref_loss(q_, k_, v_):
            return jnp.sum(self._ref(q_, k_, v_) ** 2)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        # dk/dv shapes stay at KV heads; the repeat's transpose (group sum)
        # happens inside the dkv kernel's g-dimension accumulation
        assert got[1].shape == k.shape and got[2].shape == v.shape
        for g, r, tol in zip(got, ref, (2e-4, 2e-4, 2e-4)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=1e-3, atol=tol)

    def test_rejects_bad_kv_heads(self):
        q, k, v = self._mk(H=4, KV=2)
        with pytest.raises(ValueError, match="match and divide"):
            flash_attention(q, k[:, :1], v, block_q=64, block_k=64)  # 1 vs 2
        _, k3, v3 = self._mk(H=4, KV=3)
        with pytest.raises(ValueError, match="match and divide"):
            flash_attention(q, k3, v3, block_q=64, block_k=64)  # 4 % 3


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_dense(self, causal):
        q, k, v = mk_qkv(T=128)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
            return jnp.sum(jnp.sin(o))  # nontrivial cotangent

        def loss_dense(q, k, v):
            return jnp.sum(jnp.sin(dense_attention(q, k, v, causal=causal)))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=f"d{name} mismatch",
            )


class TestFlashModel:
    def test_model_flash_matches_dense(self):
        from saturn_tpu.models.gpt2 import build_gpt2

        dense = build_gpt2("test-tiny")
        flash = build_gpt2("test-tiny", attention="flash")
        params = dense.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 255)
        ld = dense.apply_fn(params, tokens)
        lf = flash.apply_fn(params, tokens)
        np.testing.assert_allclose(np.asarray(ld), np.asarray(lf),
                                   rtol=2e-2, atol=2e-2)

    @pytest.mark.slow
    def test_model_flash_trains(self):
        from saturn_tpu.models.gpt2 import build_gpt2
        from tests.test_models import check_trains

        check_trains(build_gpt2("test-tiny", attention="flash"))

    def test_attention_validated(self):
        from saturn_tpu.models.gpt2 import config_for

        with pytest.raises(ValueError, match="attention"):
            config_for("test-tiny", attention="fast")
