"""Online job service: queue lifecycle, admission, service loop, acceptance.

Everything runs hardware-free on the 8 virtual CPU devices from conftest.
The acceptance test at the bottom is the ISSUE's scenario: ≥6 jobs with
staggered arrivals and mixed priorities submitted to a running service, one
mid-run slice preemption, and the asserts that all non-evicted jobs
complete, a later-arriving high-priority job starts before a queued
low-priority one, warm-cached arrivals admit with zero trials, and the
JSONL stream carries every job's full lifecycle.
"""

import threading
import time

import pytest

from saturn_tpu import library
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.service import (
    AdmissionController,
    JobRequest,
    JobState,
    SaturnService,
    ServiceClient,
    SubmissionQueue,
)
from saturn_tpu.service.admission import ADMIT, DEFER, REJECT, compute_weight
from saturn_tpu.utils.metrics import read_events

pytestmark = pytest.mark.service


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class RecordingTech(BaseTechnique):
    """Sleeps per batch; records (task, block-size) launches."""

    name = "svc-fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.calls.append((task.name, len(devices)))
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FailingTech(RecordingTech):
    """Raises on execute for tasks named in ``fail``."""

    name = "svc-failing"

    def __init__(self, fail=(), **kw):
        super().__init__(**kw)
        self.fail = set(fail)

    def execute(self, task, devices, tid, override_batch_count=None):
        if task.name in self.fail:
            raise RuntimeError(f"injected failure for {task.name}")
        super().execute(task, devices, tid, override_batch_count)


class FakeTask:
    """Duck-typed pre-profiled task (admission skips the trial sweep)."""

    def __init__(self, name, total_batches, sizes, tech, pbt=0.001, hints=None):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = dict(hints or {})
        self.chip_range = None
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


def _superlinear_pbt(n_devices: int) -> float:
    # larger blocks are disproportionately faster, so the makespan-optimal
    # schedule is full-mesh tasks serialized — start order is then exactly
    # the priority-weight order the tests assert on
    return 0.0035 * (8.0 / n_devices) ** 1.5


class ProfiledTech(BaseTechnique):
    """Library-registered technique for the real admission/profiling path.

    Class-level recording: the evaluator instantiates the class itself."""

    name = "svc-prof"
    launches = []
    lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with ProfiledTech.lock:
            ProfiledTech.launches.append((task.name, len(devices)))
        time.sleep(_superlinear_pbt(len(devices)) * (override_batch_count or 1))

    def search(self, task, devices, tid):
        time.sleep(0.004)  # stand-in for compile cost
        return {}, _superlinear_pbt(len(devices))


class ProfilableTask(FakeTask):
    """No pre-filled strategies: admission must run (or cache-hit) the
    sweep. ``family`` distinguishes cache fingerprints between task shapes
    (fake tasks otherwise all degrade to the same model signature)."""

    # 140 batches ≈ two full 0.25s intervals at pbt(8): every execution
    # cycle then runs the full interval, so the acceptance test's
    # mid-interval watchdog (after_s=0.05) always fires before the engine
    # returns and cancels it
    def __init__(self, name, family, total_batches=140):
        super().__init__(name, total_batches, [], None,
                         hints={"family": family})
        self.strategies = {}
        # profile size 8 only: every schedule is then a full-mesh serial
        # chain (makespan-equal), so start order is decided purely by the
        # admission weights. After the slice preemption shrinks the mesh,
        # survivors get a size-4 strategy from the replanner's Amdahl
        # synthesis, and preempted requeues from admission's.
        self.chip_range = (8,)


# --------------------------------------------------------------------- queue
class TestSubmissionQueue:
    def test_submit_drain_fifo(self):
        q = SubmissionQueue()
        tech = RecordingTech()
        recs = [
            q.submit(JobRequest(FakeTask(f"t{i}", 10, [2], tech)))
            for i in range(3)
        ]
        assert [r.state for r in recs] == [JobState.QUEUED] * 3
        assert q.depth() == 3
        drained = q.drain()
        assert [r.name for r in drained] == ["t0", "t1", "t2"]
        assert q.drain() == []

    def test_unique_live_names_enforced(self):
        q = SubmissionQueue()
        tech = RecordingTech()
        q.submit(JobRequest(FakeTask("dup", 10, [2], tech)))
        with pytest.raises(ValueError, match="unique among active jobs"):
            q.submit(JobRequest(FakeTask("dup", 10, [2], tech)))

    def test_name_reusable_after_terminal(self):
        q = SubmissionQueue()
        tech = RecordingTech()
        r1 = q.submit(JobRequest(FakeTask("re", 10, [2], tech)))
        q.mark(r1, JobState.PROFILING)
        q.mark(r1, JobState.FAILED, error="nope")
        r2 = q.submit(JobRequest(FakeTask("re", 10, [2], tech)))
        assert r2.job_id != r1.job_id

    def test_illegal_transition_raises(self):
        q = SubmissionQueue()
        rec = q.submit(JobRequest(FakeTask("x", 10, [2], RecordingTech())))
        with pytest.raises(RuntimeError, match="illegal job transition"):
            q.mark(rec, JobState.RUNNING)  # QUEUED -> RUNNING skips stages

    def test_lifecycle_timestamps(self):
        q = SubmissionQueue()
        rec = q.submit(JobRequest(FakeTask("x", 10, [2], RecordingTech())))
        q.mark(rec, JobState.PROFILING)
        q.mark(rec, JobState.SCHEDULED)
        q.mark(rec, JobState.RUNNING)
        q.mark(rec, JobState.DONE)
        assert (rec.submitted_at <= rec.admitted_at <= rec.scheduled_at
                <= rec.started_at <= rec.finished_at)

    def test_preemption_requeue_roundtrip(self):
        q = SubmissionQueue()
        rec = q.submit(JobRequest(FakeTask("p", 10, [2], RecordingTech())))
        q.drain()
        q.mark(rec, JobState.PROFILING)
        q.mark(rec, JobState.SCHEDULED)
        q.mark(rec, JobState.RUNNING)
        started = rec.started_at
        q.requeue(rec)  # preempted: RUNNING -> QUEUED, back on arrivals
        assert rec.state is JobState.QUEUED and rec.requeues == 1
        assert [r.name for r in q.drain()] == ["p"]
        q.mark(rec, JobState.PROFILING)
        q.mark(rec, JobState.SCHEDULED)
        q.mark(rec, JobState.RUNNING)
        assert rec.started_at == started  # first-launch stamp is sticky

    def test_wait_timeout_and_cancel(self):
        q = SubmissionQueue()
        rec = q.submit(JobRequest(FakeTask("w", 10, [2], RecordingTech())))
        with pytest.raises(TimeoutError):
            q.wait(rec.job_id, timeout=0.05)
        assert q.cancel(rec.job_id) is True   # QUEUED -> evicted immediately
        assert rec.state is JobState.EVICTED
        assert q.cancel(rec.job_id) is False  # already terminal
        assert q.wait(rec.job_id, timeout=1.0).state is JobState.EVICTED
        assert q.drain() == []  # cancelled arrival removed from the queue


# ----------------------------------------------------------------- admission
class TestAdmission:
    def _ctrl(self, t, **kw):
        q = SubmissionQueue()
        return AdmissionController(t, q, **kw), q

    def test_preprofiled_admits_with_zero_trials(self):
        t8 = topo(8)
        ctrl, q = self._ctrl(t8)
        task = FakeTask("a", 10, [2, 4], RecordingTech())
        rec = q.submit(JobRequest(task, priority=2.0))
        dec = ctrl.admit(rec, t8)
        assert dec.action == ADMIT and dec.trials_run == 0
        assert dec.weight == pytest.approx(4.0)  # 2^priority, no deadline
        assert task.hints["priority"] == 2.0  # replanner eviction ordering

    def test_reject_when_never_fits(self):
        t8 = topo(8)
        ctrl, q = self._ctrl(t8)
        rec = q.submit(JobRequest(FakeTask("big", 10, [16], RecordingTech())))
        dec = ctrl.admit(rec, t8)
        assert dec.action == REJECT
        assert "fits the mesh" in dec.reason

    def test_defer_on_degraded_mesh(self):
        ctrl, q = self._ctrl(topo(8))  # base capacity 8
        rec = q.submit(JobRequest(FakeTask("d", 10, [8], RecordingTech())))
        dec = ctrl.admit(rec, topo(4))  # shrunken current mesh
        assert dec.action == DEFER
        assert "degraded" in dec.reason

    def test_weight_formula(self):
        assert compute_weight(3.0, None, 10.0) == pytest.approx(8.0)
        # deadline boost: est/slack, capped at 2x when slack <= est
        assert compute_weight(0.0, 10.0, 5.0) == pytest.approx(1.5)
        assert compute_weight(0.0, 1.0, 5.0) == pytest.approx(2.0)
        # urgency never outranks a whole priority class (2x cap)
        assert compute_weight(1.0, None, 0.0) >= compute_weight(0.0, 0.1, 5.0)

    def test_warm_arrival_zero_trials_via_profile_cache(self, tmp_path):
        library.register("svc-prof", ProfiledTech)
        try:
            t8 = topo(8)
            cache = str(tmp_path / "pcache")
            ctrl, q = self._ctrl(
                t8, technique_names=["svc-prof"], profile_cache=cache
            )
            cold = q.submit(JobRequest(ProfilableTask("cold", family=1)))
            dec_cold = ctrl.admit(cold, t8)
            assert dec_cold.action == ADMIT and dec_cold.trials_run > 0
            # same fingerprint (family), different name and priority
            warm = q.submit(JobRequest(ProfilableTask("warm", family=1),
                                       priority=3.0))
            dec_warm = ctrl.admit(warm, t8)
            assert dec_warm.action == ADMIT
            assert dec_warm.trials_run == 0  # O(cache lookup) admission
            assert warm.task.feasible_strategies()
            # a different family is a different fingerprint: cold again
            other = q.submit(JobRequest(ProfilableTask("other", family=2)))
            assert ctrl.admit(other, t8).trials_run > 0
        finally:
            library.deregister("svc-prof")


# -------------------------------------------------------------- service loop
class TestServiceLoop:
    def test_jobs_complete_with_lifecycle_events(self, tmp_path):
        mpath = str(tmp_path / "m.jsonl")
        tech = RecordingTech()
        svc = SaturnService(topology=topo(8), interval=0.2,
                            metrics_path=mpath, poll_s=0.02).start()
        client = ServiceClient(svc)
        try:
            ids = [
                client.submit(FakeTask(f"job{i}", 50, [2, 4], tech),
                              priority=float(i))
                for i in range(3)
            ]
            outs = [client.wait(j, timeout=60) for j in ids]
        finally:
            svc.stop(timeout=30)
        assert all(o["state"] == "DONE" for o in outs)
        evs = read_events(mpath)
        for jid in ids:
            kinds = [e["kind"] for e in evs if e.get("job") == jid]
            for k in ("job_submitted", "job_admitted", "job_scheduled",
                      "job_completed"):
                assert k in kinds, (jid, k, kinds)
        assert read_events(mpath, kind="queue_depth")

    def test_cancel_running_job(self, tmp_path):
        mpath = str(tmp_path / "m.jsonl")
        tech = RecordingTech(per_batch=0.01)
        svc = SaturnService(topology=topo(8), interval=0.15,
                            metrics_path=mpath, poll_s=0.02).start()
        client = ServiceClient(svc)
        try:
            jid = client.submit(FakeTask("longjob", 400, [4], tech, pbt=0.01))
            deadline = time.monotonic() + 20
            while client.status(jid)["state"] in ("QUEUED", "PROFILING"):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert client.cancel(jid) is True
            out = client.wait(jid, timeout=30)
        finally:
            svc.stop(timeout=30)
        assert out["state"] == "EVICTED"
        assert any(e.get("job") == jid
                   for e in read_events(mpath, kind="job_evicted"))

    def test_failed_job_retries_then_fails_isolated(self, tmp_path):
        mpath = str(tmp_path / "m.jsonl")
        bad_tech = FailingTech(fail={"bad"})
        good_tech = RecordingTech()
        svc = SaturnService(topology=topo(8), interval=0.15,
                            metrics_path=mpath, poll_s=0.02).start()
        client = ServiceClient(svc)
        try:
            jbad = client.submit(FakeTask("bad", 30, [2], bad_tech),
                                 max_retries=1)
            jgood = client.submit(FakeTask("good", 30, [2], good_tech))
            bad = client.wait(jbad, timeout=60)
            good = client.wait(jgood, timeout=60)
        finally:
            svc.stop(timeout=30)
        assert bad["state"] == "FAILED" and bad["attempts"] == 2
        assert good["state"] == "DONE"  # failure isolation
        assert read_events(mpath, kind="task_retry")
        assert any(e.get("job") == jbad
                   for e in read_events(mpath, kind="job_failed"))

    def test_retry_budget_exhaustion_is_terminal_and_journaled(self, tmp_path):
        """Satellite check for the durability layer: a job that exhausts its
        retry budget lands in FAILED *terminally* — the journal holds the
        FAILED record (so a restart replays it as terminal, not re-runnable)
        and the task name is immediately reusable."""
        wal = str(tmp_path / "wal")
        bad_tech = FailingTech(fail={"bad"})
        svc = SaturnService(topology=topo(8), interval=0.15, poll_s=0.02,
                            durability_dir=wal).start()
        client = ServiceClient(svc)
        try:
            jbad = client.submit(FakeTask("bad", 30, [2], bad_tech),
                                 max_retries=1)
            out = client.wait(jbad, timeout=60)
            assert out["state"] == "FAILED" and out["attempts"] == 2
            # terminal failure released the name: resubmission under the
            # same task name admits cleanly
            jre = client.submit(FakeTask("bad", 20, [2], RecordingTech()))
            assert client.wait(jre, timeout=60)["state"] == "DONE"
        finally:
            svc.stop(timeout=30)

        from saturn_tpu.durability import replay, replay_service_state

        states = [r["data"]["state"] for r in replay(wal, strict=True)
                  if r["kind"] == "job_state" and r["data"]["job"] == jbad]
        assert states[-1] == "FAILED"
        # a restart would replay the job as terminal — no resurrection, no
        # task_provider required
        replayed = replay_service_state(wal)
        assert replayed.jobs[jbad].terminal
        assert replayed.jobs[jbad].error
        assert not [j for j in replayed.live_jobs()]

    def test_admission_pressure_sheds_lowest_priority(self, tmp_path):
        """Deadline slack exhausted -> the service reuses the replanner's
        evict-lowest-priority policy to shed load."""
        mpath = str(tmp_path / "m.jsonl")
        tech = RecordingTech(per_batch=0.005)
        svc = SaturnService(topology=topo(8), interval=0.2,
                            metrics_path=mpath, poll_s=0.02).start()
        client = ServiceClient(svc)
        try:
            # two full-mesh jobs serialize: ~0.5s each, but the deadline
            # only leaves room for one
            jhi = client.submit(FakeTask("hi", 100, [8], tech, pbt=0.005),
                                priority=2.0, deadline_s=0.7)
            jlo = client.submit(FakeTask("lo", 100, [8], tech, pbt=0.005),
                                priority=0.0)
            hi = client.wait(jhi, timeout=60)
            lo = client.wait(jlo, timeout=60)
        finally:
            svc.stop(timeout=30)
        assert hi["state"] == "DONE"
        assert lo["state"] == "EVICTED"
        evs = [e for e in read_events(mpath, kind="job_evicted")
               if e.get("job") == jlo]
        assert evs and evs[0]["reason"] == "admission-pressure"


# ---------------------------------------------------------------- acceptance
class TestAcceptance:
    def test_online_service_seeded_scenario(self, tmp_path):
        """ISSUE acceptance: ≥6 staggered mixed-priority jobs, one mid-run
        slice preemption, all non-evicted jobs complete, later-arriving
        high-priority starts before queued low-priority, warm arrivals admit
        with zero trials, full JSONL lifecycle per job."""
        from saturn_tpu.resilience import (
            FaultEvent,
            FaultInjector,
            FaultKind,
            FleetHealthMonitor,
        )

        library.register("svc-prof", ProfiledTech)
        ProfiledTech.launches = []
        mpath = str(tmp_path / "svc.jsonl")
        t8 = topo(8)
        monitor = FleetHealthMonitor.for_topology(t8)
        injector = FaultInjector(schedule=[
            FaultEvent(4, FaultKind.SLICE_PREEMPTION, devices=(4, 5, 6, 7),
                       after_s=0.05),
        ])
        svc = SaturnService(
            topology=t8, interval=0.25, metrics_path=mpath,
            technique_names=["svc-prof"],
            profile_cache=str(tmp_path / "pcache"),
            health_monitor=monitor, fault_injector=injector,
            poll_s=0.02,
        ).start()
        client = ServiceClient(svc)
        try:
            ids = {}
            ids["j0"] = client.submit(ProfilableTask("j0", family=0),
                                      priority=1.0)
            ids["j1"] = client.submit(ProfilableTask("j1", family=1),
                                      priority=1.0)
            time.sleep(0.1)
            # later-arriving high priority vs queued low priority: submitted
            # back to back so both land in the same admission drain
            ids["jlow"] = client.submit(ProfilableTask("jlow", family=2),
                                        priority=0.0)
            ids["jhigh"] = client.submit(ProfilableTask("jhigh", family=3),
                                         priority=5.0)
            # wait for j0's profile to land in the cache, then submit a
            # same-fingerprint job: must admit warm (zero trials)
            deadline = time.monotonic() + 30
            while client.status(ids["j0"])["state"] in ("QUEUED", "PROFILING"):
                assert time.monotonic() < deadline, "j0 never admitted"
                time.sleep(0.01)
            ids["j4"] = client.submit(ProfilableTask("j4", family=4),
                                      priority=2.0)
            ids["jwarm"] = client.submit(ProfilableTask("jwarm", family=0),
                                         priority=1.0)
            assert len(ids) >= 6
            outs = {k: client.wait(j, timeout=120) for k, j in ids.items()}
        finally:
            svc.stop(timeout=60)
            library.deregister("svc-prof")

        # 1. all non-evicted jobs complete (none should be evicted here:
        #    no deadlines, and preempted work requeues instead of dying)
        assert all(o["state"] == "DONE" for o in outs.values()), outs

        # 2. the later-arriving high-priority job started first
        assert outs["jhigh"]["submitted_at"] > outs["jlow"]["submitted_at"]
        assert outs["jhigh"]["started_at"] < outs["jlow"]["started_at"], (
            outs["jhigh"], outs["jlow"],
        )

        # 3. warm-cached arrival admitted without running new trials
        evs = read_events(mpath)
        admits = {}  # first admit per job: requeued re-admissions are warm
        for e in evs:
            if e["kind"] == "job_admitted" and e["decision"] == "admit":
                admits.setdefault(e["job"], e)
        assert admits[ids["j0"]]["trials_run"] > 0          # cold
        assert admits[ids["jwarm"]]["trials_run"] == 0      # warm
        assert admits[ids["jwarm"]]["warm"] is True

        # 4. the preemption actually happened mid-run and requeued through
        #    the queue (no retry consumed, job still completed)
        assert any(e["kind"] == "task_preempted" for e in evs)
        changes = [e for e in evs if e["kind"] == "topology_change"]
        assert any(c.get("change") == "shrink" or c.get("kind_detail") ==
                   "shrink" or c.get("lost") for c in changes), changes
        preempted_tasks = {e["task"] for e in evs
                           if e["kind"] == "task_preempted"}
        preempted_jobs = [r for k, r in outs.items()
                          if r["task"] in preempted_tasks]
        assert preempted_jobs and all(r["requeues"] >= 1
                                      for r in preempted_jobs)

        # 5. full lifecycle per job in the JSONL stream
        for key, jid in ids.items():
            kinds = [e["kind"] for e in evs if e.get("job") == jid]
            for k in ("job_submitted", "job_admitted", "job_scheduled",
                      "job_completed"):
                assert k in kinds, (key, k, kinds)
        assert any(e["kind"] == "queue_depth" for e in evs)
