"""Aux subsystems: metrics stream, profiler trace wrapper, failure isolation.

These fill the gaps SURVEY.md §5 identifies in the reference (no metrics
files, no tracer, no failure isolation — a child-process crash killed the
whole batch).
"""

import json

import pytest

from saturn_tpu import HParams, Task, library
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.utils import metrics
from saturn_tpu.utils.trace import profile_trace


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


class TestMetrics:
    def test_writer_and_global(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        metrics.configure(p)
        try:
            metrics.event("trial", task="a", feasible=True)
            metrics.event("interval", elapsed_s=1.5)
        finally:
            metrics.configure(None)
        evs = read_events(p)
        assert [e["kind"] for e in evs] == ["trial", "interval"]
        assert evs[0]["task"] == "a" and "ts" in evs[0]
        # unconfigured -> no-op, no error
        metrics.event("ignored")

    def test_thread_safety(self, tmp_path):
        import threading

        p = str(tmp_path / "m.jsonl")
        metrics.configure(p)
        try:
            ths = [
                threading.Thread(
                    target=lambda i=i: [metrics.event("e", i=i) for _ in range(50)]
                )
                for i in range(4)
            ]
            [t.start() for t in ths]
            [t.join() for t in ths]
        finally:
            metrics.configure(None)
        evs = read_events(p)  # every line must be valid JSON (no interleaving)
        assert len(evs) == 200

    def test_read_events_skips_truncated_tail(self, tmp_path):
        # A live tail of an in-flight run: the writer is mid-append, so the
        # last line has no newline and is not valid JSON yet.
        p = str(tmp_path / "torn.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "solve"}) + "\n")
            f.write(json.dumps({"ts": 2.0, "kind": "interval"}) + "\n")
            f.write('{"ts": 3.0, "kind": "tru')  # torn tail, no newline
        evs = metrics.read_events(p)
        assert [e["kind"] for e in evs] == ["solve", "interval"]
        assert metrics.read_events(p, kind="interval")[0]["ts"] == 2.0

    def test_tail_events_buffers_partial_line(self, tmp_path):
        # tail_events must never yield a truncated record: the torn tail is
        # buffered and delivered only once its newline lands.
        p = str(tmp_path / "tail.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"kind": "a"}) + "\n")
            f.write('{"kind": "b"')  # partial
        got = list(metrics.tail_events(p, follow=False))
        assert [e["kind"] for e in got] == ["a"]
        with open(p, "a") as f:
            f.write(', "x": 1}\n')
        got = list(metrics.tail_events(p, follow=False))
        assert [e["kind"] for e in got] == ["a", "b"]
        assert got[1]["x"] == 1


class TestBufferedMetrics:
    """Round 10: emission is buffered off the hot path — events hit disk in
    batches at size/latency thresholds or an explicit interval-boundary
    flush, and the whole-line torn-tail contract survives batching."""

    def test_events_buffer_until_flush(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        w = metrics.MetricsWriter(p, max_buffered=256, max_latency_s=3600.0)
        try:
            for i in range(10):
                w.event("step", i=i)
            assert read_events(p) == []  # nothing written yet: no syscalls
            w.flush()
            evs = read_events(p)
            assert [e["i"] for e in evs] == list(range(10))
        finally:
            w.close()

    def test_size_threshold_auto_drains(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        w = metrics.MetricsWriter(p, max_buffered=4, max_latency_s=3600.0)
        try:
            for i in range(3):
                w.event("step", i=i)
            assert read_events(p) == []
            w.event("step", i=3)  # 4th event crosses max_buffered
            assert len(read_events(p)) == 4
        finally:
            w.close()

    def test_latency_threshold_auto_drains(self, tmp_path, monkeypatch):
        p = str(tmp_path / "m.jsonl")
        w = metrics.MetricsWriter(p, max_buffered=10_000, max_latency_s=2.0)
        clock = [100.0]
        monkeypatch.setattr(metrics.time, "monotonic", lambda: clock[0])
        try:
            w.event("a")
            clock[0] += 1.0
            w.event("b")
            assert read_events(p) == []  # oldest is 1s old: under the bound
            clock[0] += 1.5
            w.event("c")  # oldest now 2.5s old: time-bounded drain
            assert [e["kind"] for e in read_events(p)] == ["a", "b", "c"]
        finally:
            w.close()

    def test_close_drains_buffer(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        w = metrics.MetricsWriter(p, max_buffered=256, max_latency_s=3600.0)
        w.event("last", x=1)
        w.close()
        assert read_events(p)[0]["x"] == 1

    def test_batched_drain_writes_whole_lines(self, tmp_path):
        """One write() per drain, every line newline-terminated — the
        guarantee read_events/tail_events' torn-tail handling relies on."""
        p = str(tmp_path / "m.jsonl")
        w = metrics.MetricsWriter(p, max_buffered=256, max_latency_s=3600.0)
        try:
            for i in range(5):
                w.event("step", i=i)
            w.flush()
            with open(p) as f:
                raw = f.read()
            assert raw.endswith("\n")
            assert len(raw.strip().splitlines()) == 5
        finally:
            w.close()

    def test_module_flush_noop_when_unconfigured(self):
        metrics.flush()  # must not raise with no writer configured

    def test_module_flush_drains_global_writer(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        metrics.configure(p)
        try:
            metrics.event("interval", n=1)
            metrics.flush()
            assert read_events(p)[0]["kind"] == "interval"
        finally:
            metrics.configure(None)


class TestTopLevelAPI:
    def test_orchestrate_signature_parity(self):
        # The top-level wrapper must forward every orchestrator kwarg
        # explicitly — same names, order and defaults (ISSUE: it used to pin
        # interval=1000 as an int and hide the rest behind **kw).
        import inspect

        import saturn_tpu
        from saturn_tpu.executor.orchestrator import orchestrate as real

        wrap = inspect.signature(saturn_tpu.orchestrate).parameters
        ref = inspect.signature(real).parameters
        assert list(wrap) == list(ref)
        for name, p in ref.items():
            assert wrap[name].default == p.default, name


class TestTrace:
    def test_noop_without_dir(self):
        with profile_trace(None):
            pass

    def test_writes_trace(self, tmp_path):
        import os

        d = str(tmp_path / "trace")
        with profile_trace(d):
            import jax
            import jax.numpy as jnp

            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        # jax writes plugins/profile/<date>/ under the dir
        assert os.path.isdir(d) and os.listdir(d)

    def test_body_exception_propagates(self, tmp_path):
        with pytest.raises(RuntimeError, match="boom"):
            with profile_trace(str(tmp_path / "t2")):
                raise RuntimeError("boom")


class FlakyTechnique(BaseTechnique):
    """Succeeds search; explodes on execute for tasks named 'bad*'."""

    name = "flaky"

    def search(self, task, devices, tid):
        return {}, 0.01

    def execute(self, task, devices, tid, override_batch_count=None):
        if task.name.startswith("bad"):
            # simulate device state cached before the crash
            task._live_state = ("key", object())
            raise RuntimeError(f"injected failure for {task.name}")
        import numpy as np

        np.savez(task.ckpt_path, step=override_batch_count or 0)


class FlakyOnceTechnique(BaseTechnique):
    """Fails the FIRST execute call per task, succeeds afterwards."""

    name = "flaky-once"
    _failed = None

    def __init__(self):
        self._failed = set()

    def search(self, task, devices, tid):
        return {}, 0.01

    def execute(self, task, devices, tid, override_batch_count=None):
        if task.name.startswith("flaky") and task.name not in self._failed:
            self._failed.add(task.name)
            raise RuntimeError(f"injected one-shot failure for {task.name}")
        import numpy as np

        prev = 0
        if task.has_ckpt():
            prev = int(np.load(task.ckpt_path)["step"])
        np.savez(task.ckpt_path, step=prev + (override_batch_count or 0))


def mk_task(name, tmp_path, batches=4):
    t = Task(
        get_model=lambda **kw: None,
        get_dataloader=lambda: FakeDS(),
        loss_fn=lambda a, b: 0.0,
        hparams=HParams(lr=1e-3, batch_count=batches),
        name=name,
        save_dir=str(tmp_path / "ckpts"),
    )
    return t


class FakeDS:
    batch_size = 4
    context_length = 8

    def __len__(self):
        return 4

    def batch(self, i):
        import numpy as np

        return np.zeros((4, 8), dtype=np.int32)

    def example_batch(self):
        return self.batch(0)


class TestFailureIsolation:
    def _setup(self, tmp_path):
        import saturn_tpu

        library.register("flaky", FlakyTechnique)
        good = mk_task("good-task", tmp_path)
        bad = mk_task("bad-task", tmp_path)
        tech = FlakyTechnique()
        for t in (good, bad):
            t.strategies[1] = Strategy(tech, 1, {}, 1.0, per_batch_time=0.01)
        return saturn_tpu, good, bad

    def test_drop_policy_evicts_and_continues(self, tmp_path):
        saturn_tpu, good, bad = self._setup(tmp_path)
        res = saturn_tpu.orchestrate(
            [good, bad], interval=10.0, failure_policy="drop",
            metrics_path=str(tmp_path / "m.jsonl"),
        )
        assert res["completed"] == ["good-task"]
        assert "bad-task" in res["failed"]
        kinds = [e["kind"] for e in read_events(str(tmp_path / "m.jsonl"))]
        assert "task_failed" in kinds and "task_completed" in kinds
        assert "solve" in kinds and "interval" in kinds
        # the scoped writer must be restored on exit: later events are no-ops
        n = len(kinds)
        metrics.event("leak-check")
        assert len(read_events(str(tmp_path / "m.jsonl"))) == n
        # evicted task's cached device state must be freed (HBM release)
        assert bad._live_state is None

    def test_scoped_survives_inner_configure(self, tmp_path):
        """configure() inside a scoped region must not crash the exit path
        or close the user's replacement writer."""
        p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        with metrics.scoped(p1):
            metrics.event("one")
            metrics.configure(p2)  # replaces (and closes) the scoped writer
            metrics.event("two")
        metrics.event("three")  # p2 writer still active after scoped exit
        metrics.configure(None)
        assert [e["kind"] for e in read_events(p1)] == ["one"]
        assert [e["kind"] for e in read_events(p2)] == ["two", "three"]

    def test_raise_policy_crashes_batch(self, tmp_path):
        saturn_tpu, good, bad = self._setup(tmp_path)
        with pytest.raises(RuntimeError, match="bad-task"):
            saturn_tpu.orchestrate([good, bad], interval=10.0)

    def test_invalid_policy_rejected(self, tmp_path):
        saturn_tpu, good, _ = self._setup(tmp_path)
        with pytest.raises(ValueError, match="failure_policy"):
            saturn_tpu.orchestrate([good], interval=10.0, failure_policy="explode")

    def test_retry_policy_recovers_flaky_task(self, tmp_path):
        """A task that fails once then succeeds must complete under
        failure_policy='retry' (resuming at the next interval)."""
        import saturn_tpu

        library.register("flaky-once", FlakyOnceTechnique)
        tech = FlakyOnceTechnique()
        t1 = mk_task("flaky-once-task", tmp_path)
        t2 = mk_task("steady-task", tmp_path)
        for t in (t1, t2):
            t.strategies[1] = Strategy(tech, 1, {}, 1.0, per_batch_time=0.01)
        res = saturn_tpu.orchestrate(
            [t1, t2], interval=10.0, failure_policy="retry",
            metrics_path=str(tmp_path / "mr.jsonl"),
        )
        assert sorted(res["completed"]) == ["flaky-once-task", "steady-task"]
        assert res["failed"] == {}
        kinds = [e["kind"] for e in read_events(str(tmp_path / "mr.jsonl"))]
        assert "task_retry" in kinds and "task_failed" not in kinds
        # the retried attempt re-ran the rolled-back batches
        import numpy as np  # noqa: F401

        from saturn_tpu.utils import checkpoint as _ck

        assert int(_ck.load_arrays(t1.ckpt_path)["step"]) == 4

    def test_retry_policy_evicts_after_budget(self, tmp_path):
        """An always-failing task is evicted once retries are exhausted."""
        saturn_tpu, good, bad = self._setup(tmp_path)
        res = saturn_tpu.orchestrate(
            [good, bad], interval=10.0, failure_policy="retry",
            max_task_retries=2, metrics_path=str(tmp_path / "me.jsonl"),
        )
        assert res["completed"] == ["good-task"]
        assert "bad-task" in res["failed"]
        events = read_events(str(tmp_path / "me.jsonl"))
        assert sum(e["kind"] == "task_retry" for e in events) == 2
        assert sum(e["kind"] == "task_failed" for e in events) == 1


class TestAsyncCheckpoint:
    """save_async: device->host copy synchronous, disk write overlapped;
    exists/restore/flush join the in-flight write (no torn reads)."""

    def test_roundtrip_and_visibility(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from saturn_tpu.utils import checkpoint as ckpt

        tree = {"a": jnp.arange(8.0), "b": {"c": jnp.ones((4, 4))}}
        p = str(tmp_path / "s.npz")
        ckpt.save_async(p, tree)
        assert ckpt.exists(p)  # joins the write
        out = ckpt.restore(p, tree)
        np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(8.0))

    def test_second_save_wins(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from saturn_tpu.utils import checkpoint as ckpt

        p = str(tmp_path / "s.npz")
        ckpt.save_async(p, {"x": jnp.zeros(4)})
        ckpt.save_async(p, {"x": jnp.ones(4)})  # waits for the first
        ckpt.flush()
        out = ckpt.restore(p, {"x": jnp.zeros(4)})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.ones(4))

    def test_write_failure_surfaces(self, tmp_path):
        """A failed background write must re-raise at the next join point,
        not silently report the checkpoint as saved."""
        import jax.numpy as jnp

        from saturn_tpu.utils import checkpoint as ckpt

        blocker = tmp_path / "not-a-dir"
        blocker.write_text("file where a directory is needed")
        bad = str(blocker / "sub" / "s.npz")  # makedirs will fail
        ckpt.save_async(bad, {"x": jnp.zeros(2)})
        with pytest.raises(RuntimeError, match="async checkpoint write"):
            ckpt.flush()
        # the error is consumed; later flushes are clean
        ckpt.flush()


class TestWriterRank:
    """Multi-host checkpoint writer selection (utils/checkpoint._writer_rank):
    the lowest process index addressing the tree writes it."""

    def test_host_trees_default_to_rank0(self):
        import numpy as np

        from saturn_tpu.utils.checkpoint import _writer_rank

        assert _writer_rank({"a": np.ones(3)}) == 0

    def test_device_tree_uses_lowest_addressing_process(self):
        import numpy as np

        from saturn_tpu.utils.checkpoint import _writer_rank

        class FakeDev:
            def __init__(self, pi):
                self.process_index = pi

        class FakeSharding:
            def __init__(self, pis):
                self.device_set = {FakeDev(p) for p in pis}

        class FakeLeaf:
            def __init__(self, pis):
                self.sharding = FakeSharding(pis)

        assert _writer_rank({"w": FakeLeaf([2, 3])}) == 2
        assert _writer_rank({"w": FakeLeaf([0, 1, 2])}) == 0
        # host (no-sharding) leaves are skipped in favor of device leaves
        assert _writer_rank({"a": np.ones(2), "w": FakeLeaf([1])}) == 1
