"""Elastic resilience: fault injection, health monitoring, replan, migration.

Everything here runs hardware-free on the 8 virtual CPU devices from
conftest. The acceptance test at the bottom is the ISSUE's scenario: a
seeded run that loses a 4-device slice mid-interval must still complete
every task, with the replanner shrinking the plan and migrated tasks
resuming from their checkpoints on the surviving mesh.
"""

import threading
import time

import numpy as np
import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.executor import orchestrate
from saturn_tpu.resilience import (
    ElasticReplanner,
    FaultEvent,
    FaultInjector,
    FaultKind,
    FleetHealthMonitor,
    PreemptedError,
    seeded_schedule,
)
from saturn_tpu.solver import milp
from saturn_tpu.utils.metrics import read_events

pytestmark = pytest.mark.resilience


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class RecordingTech(BaseTechnique):
    """Sleeps per batch; records (task, block-size, batches) calls."""

    name = "fake"

    def __init__(self, per_batch=0.001):
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        time.sleep(self.per_batch * (override_batch_count or 1))
        with self.lock:
            self.calls.append((task.name, len(devices), override_batch_count))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class FakeTask:
    def __init__(self, name, total_batches, sizes, tech, pbt=0.001, hints=None):
        self.name = name
        self.total_batches = total_batches
        self.current_batch = 0
        self.epoch_length = 1000
        self.hints = dict(hints or {})
        self.strategies = {
            g: Strategy(tech, g, {}, pbt * total_batches, pbt) for g in sizes
        }
        self.selected_strategy = None

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g):
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n):
        self.current_batch = (self.current_batch + n) % self.epoch_length


class TestFaultInjector:
    def test_env_parsing(self, monkeypatch):
        monkeypatch.setenv(
            "SATURN_TPU_FAULTS",
            "1+0.05:slice_preemption:4-7;2:trial_crash:jobA;3:straggler:0,2@4.5",
        )
        fi = FaultInjector.from_env()
        assert [e.kind for e in fi.schedule] == [
            FaultKind.SLICE_PREEMPTION, FaultKind.TRIAL_CRASH, FaultKind.STRAGGLER,
        ]
        pre, crash, strag = fi.schedule
        assert pre.devices == (4, 5, 6, 7) and pre.after_s == 0.05 and pre.mid_interval
        assert crash.task == "jobA" and not crash.mid_interval
        assert strag.devices == (0, 2) and strag.slowdown == 4.5

    def test_env_unset_and_garbage(self, monkeypatch):
        monkeypatch.delenv("SATURN_TPU_FAULTS", raising=False)
        assert FaultInjector.from_env() is None
        monkeypatch.setenv("SATURN_TPU_FAULTS", "nonsense")
        with pytest.raises(ValueError, match="SATURN_TPU_FAULTS"):
            FaultInjector.from_env()

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultEvent(0, "meteor_strike")

    def test_seeded_schedule_deterministic(self):
        a = seeded_schedule(42, 20, 8)
        b = seeded_schedule(42, 20, 8)
        assert a == b
        assert a != seeded_schedule(43, 20, 8)
        for e in a:
            if e.kind == FaultKind.SLICE_PREEMPTION:
                size = len(e.devices)
                assert size & (size - 1) == 0  # power-of-two block
                assert e.devices[0] % size == 0  # aligned

    def test_crash_fires_exactly_once(self):
        fi = FaultInjector(schedule=[FaultEvent(1, FaultKind.TRIAL_CRASH, task="a")])
        assert not fi.crashes("a", 0)  # wrong interval
        assert not fi.crashes("b", 1)  # wrong task
        assert fi.crashes("a", 1)
        assert not fi.crashes("a", 1)  # transient: consumed

    def test_apply_due_drives_monitor(self):
        fi = FaultInjector(schedule=[
            FaultEvent(0, FaultKind.DEVICE_LOSS, devices=(3,)),
            FaultEvent(0, FaultKind.SLICE_PREEMPTION, devices=(4, 5), after_s=0.1),
            FaultEvent(1, FaultKind.DEVICE_RETURN, devices=(3,)),
        ])
        mon = FleetHealthMonitor(8)
        applied = fi.apply_due(0, mon)
        assert [e.kind for e in applied] == [FaultKind.DEVICE_LOSS]
        assert mon.alive_indices() == [0, 1, 2, 4, 5, 6, 7]
        # the mid-interval event belongs to the watchdog, not the poll
        assert [e.devices for e in fi.due(0, mid_interval=True)] == [(4, 5)]
        fi.apply_due(1, mon)
        assert mon.alive_indices() == list(range(8))

    def test_watchdog_marks_and_aborts(self):
        fi = FaultInjector(schedule=[
            FaultEvent(0, FaultKind.SLICE_PREEMPTION, devices=(0, 1), after_s=0.02),
        ])
        mon = FleetHealthMonitor(4)
        abort = threading.Event()
        timers = fi.arm_watchdog(0, mon, abort)
        assert len(timers) == 1
        assert abort.wait(timeout=2.0)
        assert mon.alive_indices() == [2, 3]
        for t in timers:
            t.cancel()


class TestHealthMonitor:
    def test_poll_aggregation_shrink_wins(self):
        mon = FleetHealthMonitor(8)
        mon.mark_lost([4, 5], cause="slice_preemption")
        mon.mark_restored([4])  # same window: net loss of just 5
        mon.mark_lost([6])
        c = mon.poll()
        assert c.kind == "shrink" and c.lost == (5, 6)
        assert mon.poll() is None  # consumed

    def test_grow_after_return(self):
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        mon.mark_lost([7])
        mon.poll()
        mon.mark_restored([7])
        assert mon.poll() is None  # hysteresis: first healthy poll withheld
        c = mon.poll()
        assert c.kind == "grow" and c.gained == (7,)
        assert mon.alive_indices() == list(range(8))

    def test_grow_immediate_with_hysteresis_one(self):
        mon = FleetHealthMonitor(8, grow_hysteresis=1)
        mon.mark_lost([7])
        mon.poll()
        mon.mark_restored([7])
        c = mon.poll()
        assert c.kind == "grow" and c.gained == (7,)

    def test_grow_hysteresis_env_default(self, monkeypatch):
        monkeypatch.delenv("SATURN_TPU_GROW_HYSTERESIS", raising=False)
        assert FleetHealthMonitor(4).grow_hysteresis == 2
        monkeypatch.setenv("SATURN_TPU_GROW_HYSTERESIS", "3")
        assert FleetHealthMonitor(4).grow_hysteresis == 3
        monkeypatch.setenv("SATURN_TPU_GROW_HYSTERESIS", "0")
        assert FleetHealthMonitor(4).grow_hysteresis == 1  # clamped

    def test_flapping_device_one_shrink_no_churn(self):
        # A device that blinks down/up across polls yields exactly one
        # shrink and zero grow events until it stays healthy K polls.
        mon = FleetHealthMonitor(8, grow_hysteresis=2)
        mon.mark_lost([3], cause="slice_preemption")
        events = [mon.poll()]
        for _ in range(4):  # flap: return, then lose again before maturing
            mon.mark_restored([3])
            events.append(mon.poll())  # streak 1 of 2 — withheld
            mon.mark_lost([3])
            events.append(mon.poll())  # candidate dropped — no new shrink
        surfaced = [e for e in events if e is not None]
        assert len(surfaced) == 1 and surfaced[0].kind == "shrink"
        assert surfaced[0].lost == (3,)
        # Once it finally stays up, the grow surfaces after K polls.
        mon.mark_restored([3])
        assert mon.poll() is None
        c = mon.poll()
        assert c.kind == "grow" and c.gained == (3,)

    def test_shrink_flushes_hysteresis_candidates(self):
        # A shrink mid-hysteresis surfaces candidates in its gained set —
        # the replan rebuilds from the full alive set either way.
        mon = FleetHealthMonitor(8, grow_hysteresis=3)
        mon.mark_lost([6])
        mon.poll()
        mon.mark_restored([6])
        assert mon.poll() is None
        mon.mark_lost([1])
        c = mon.poll()
        assert c.kind == "shrink" and c.lost == (1,) and c.gained == (6,)
        assert mon.poll() is None  # candidate consumed by the shrink

    def test_straggler_detection_via_latency(self):
        mon = FleetHealthMonitor(8, straggler_factor=3.0)
        mon.mark_straggler([2], slowdown=5.0)
        for _ in range(3):  # injected slowdown inflates device 2's EWMA
            mon.note_step(list(range(8)), per_batch_s=0.01)
        assert mon.stragglers() == [2]
        c = mon.poll()
        assert c.kind == "degrade" and c.stragglers == (2,)

    def test_indices_and_any_lost(self):
        devs = [FakeDev() for _ in range(4)]
        mon = FleetHealthMonitor(4)
        assert mon.indices_of(devs) == []  # unbound monitor stays inert
        mon.bind_devices(devs)
        assert mon.indices_of([devs[2], devs[0]]) == [2, 0]
        mon.mark_lost([2])
        assert mon.any_lost([0, 2]) and not mon.any_lost([0, 1])
        assert mon.any_lost([99])  # unknown device counts as dead

    def test_restored_chip_forgets_history(self):
        mon = FleetHealthMonitor(2)
        mon.mark_straggler([0], slowdown=9.0)
        mon.note_step([0, 1], 0.01)
        mon.mark_lost([0])
        mon.mark_restored([0])
        assert mon._devices[0].latency_ewma is None
        assert mon._devices[0].slowdown == 1.0


class TestMeshSubset:
    def test_subset_preserves_devices_and_capacity(self):
        t = topo(8)
        sub = t.subset([0, 1, 2, 3])
        assert sub.capacity == 4
        assert sub.devices == t.devices[:4]  # same objects: id-map survives

    def test_subset_non_pow2_survivors(self):
        sub = topo(8).subset([0, 1, 2, 3, 4, 6])
        assert len(sub.devices) == 6 and sub.capacity == 4

    def test_subset_rejects_bad_indices(self):
        with pytest.raises(ValueError):
            topo(8).subset([])
        with pytest.raises(ValueError):
            topo(8).subset([0, 8])


class TestReplanner:
    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="unknown recovery policy"):
            ElasticReplanner(policy="wing-it")

    def _change(self, mon, lost):
        mon.mark_lost(lost, cause="slice_preemption")
        return mon.poll()

    def test_shrink_synthesizes_interpolated_strategy(self):
        tech = RecordingTech()
        t8 = FakeTask("only8", 50, [8], tech, pbt=0.01)
        t48 = FakeTask("both", 50, [4, 8], tech, pbt=0.01)
        base = topo(8)
        mon = FleetHealthMonitor.for_topology(base)
        prev = milp.solve([t8, t48], base)
        change = self._change(mon, [4, 5, 6, 7])
        res = ElasticReplanner().replan(
            [t8, t48], base, mon.alive_indices(), change, previous_plan=prev
        )
        assert res.topology.capacity == 4 and res.evicted == []
        assert res.synthesized == {"only8": [4]}
        assert t8.strategies[4].interpolated
        assert res.plan.assignments["only8"].apportionment <= 4
        # both tasks previously on >=4-device blocks of an 8-ring: moved
        assert any(d["moved"] for d in res.migrations.values())

    def test_unschedulable_task_evicted(self):
        tech = RecordingTech()
        # per_batch_time 0 -> no measured points -> synthesis impossible
        dead = FakeTask("dead", 10, [8], tech, pbt=0.01)
        dead.strategies[8].per_batch_time = 0.0
        ok = FakeTask("ok", 10, [4], tech, pbt=0.01)
        base = topo(8)
        mon = FleetHealthMonitor.for_topology(base)
        change = self._change(mon, [4, 5, 6, 7])
        res = ElasticReplanner().replan([dead, ok], base, mon.alive_indices(), change)
        assert res.evicted == ["dead"]
        assert set(res.plan.assignments) == {"ok"}

    def test_evict_lowest_priority_policy(self):
        tech = RecordingTech()
        hi = FakeTask("hi", 100, [2], tech, pbt=0.05, hints={"priority": 10})
        lo = FakeTask("lo", 100, [2], tech, pbt=0.05, hints={"priority": -5})
        base = topo(8)
        prev = milp.solve([hi, lo], base)
        mon = FleetHealthMonitor.for_topology(base)
        change = self._change(mon, [2, 3, 4, 5, 6, 7])
        # 2 surviving chips serialize both tasks: makespan doubles, which a
        # degrade_factor of 1.2 refuses — the low-priority task goes
        res = ElasticReplanner(
            policy="evict-lowest-priority", degrade_factor=1.2
        ).replan([hi, lo], base, mon.alive_indices(), change, previous_plan=prev)
        assert res.evicted == ["lo"]
        assert set(res.plan.assignments) == {"hi"}

    def test_degrade_in_place_skips_solver(self, monkeypatch):
        tech = RecordingTech()
        a = FakeTask("a", 20, [2, 4], tech, pbt=0.01)
        b = FakeTask("b", 20, [2, 4], tech, pbt=0.01)
        base = topo(8)
        prev = milp.solve([a, b], base)
        mon = FleetHealthMonitor.for_topology(base)
        change = self._change(mon, [4, 5, 6, 7])

        def boom(*a, **kw):  # degrade-in-place must never re-solve
            raise AssertionError("solver invoked under degrade-in-place")

        monkeypatch.setattr(milp, "solve", boom)
        res = ElasticReplanner(policy="degrade-in-place").replan(
            [a, b], base, mon.alive_indices(), change, previous_plan=prev
        )
        assert set(res.plan.assignments) == {"a", "b"}
        for asg in res.plan.assignments.values():
            assert asg.apportionment <= 4
            assert asg.block.end <= 4  # on the surviving mesh


class CheckpointingTech(BaseTechnique):
    """Batch-granular technique with real resume semantics.

    Tracks progress in a per-task npz via ``utils/checkpoint`` (the same
    module real techniques use). Mirrors real device behavior under
    preemption: if the block lost a chip mid-run, the in-flight step raises
    ``PreemptedError`` *without* checkpointing — the work is gone, exactly
    like an XLA abort — so resumed step counts stay exact.
    """

    name = "ckpt-fake"

    def __init__(self, ckpt_dir, monitor, per_batch=0.001):
        self.ckpt_dir = ckpt_dir
        self.monitor = monitor
        self.per_batch = per_batch
        self.calls = []
        self.lock = threading.Lock()

    def _path(self, task):
        return f"{self.ckpt_dir}/{task.name}.npz"

    def execute(self, task, devices, tid, override_batch_count=None):
        from saturn_tpu.utils import checkpoint as ckpt

        path = self._path(task)
        step = (
            int(ckpt.restore(path, {"step": np.zeros((), np.int64)})["step"])
            if ckpt.exists(path)
            else 0
        )
        with self.lock:
            self.calls.append((task.name, len(devices), step))
        didx = self.monitor.indices_of(devices)
        for _ in range(override_batch_count or 1):
            time.sleep(self.per_batch)
            if didx and self.monitor.any_lost(didx):
                raise PreemptedError(
                    f"simulated XLA abort for {task.name}: block lost a chip"
                )
            step += 1
        ckpt.save(path, {"step": np.asarray(step, np.int64)})

    def search(self, task, devices, tid):
        return {}, self.per_batch


class TestElasticOrchestration:
    """The ISSUE's acceptance scenario plus the retry/crash interactions."""

    def test_preemption_mid_interval_completes_all_tasks(self, tmp_path):
        base = topo(8)
        mon = FleetHealthMonitor.for_topology(base)
        tech = CheckpointingTech(str(tmp_path), mon, per_batch=0.01)
        tasks = [
            FakeTask(f"job{i}", 50, [2, 4], tech, pbt=0.01) for i in range(3)
        ]
        # interval 0 checkpoints ~25 batches/task; the preemption lands in
        # interval 1 so the post-shrink resume is from a REAL checkpoint
        fi = FaultInjector(schedule=[
            FaultEvent(1, FaultKind.SLICE_PREEMPTION, devices=(4, 5, 6, 7),
                       after_s=0.08),
        ])
        mpath = str(tmp_path / "m.jsonl")
        out = orchestrate(
            tasks, interval=0.25, topology=base, fault_injector=fi,
            health_monitor=mon, failure_policy="retry", metrics_path=mpath,
        )
        assert sorted(out["completed"]) == ["job0", "job1", "job2"]
        assert out["failed"] == {}
        # exact progress: every task ran its 50 batches exactly once
        from saturn_tpu.utils import checkpoint as ckpt

        for t in tasks:
            saved = ckpt.restore(
                f"{tmp_path}/{t.name}.npz", {"step": np.zeros((), np.int64)}
            )
            assert int(saved["step"]) == 50
        kinds = [e["kind"] for e in read_events(mpath)]
        assert "topology_change" in kinds
        assert "replan" in kinds
        assert "migration" in kinds
        assert "recovery" in kinds
        assert "task_preempted" in kinds
        assert "task_failed" not in kinds  # preemption is not failure
        change = read_events(mpath, kind="topology_change")[0]
        assert change["change"] == "shrink" and change["lost"] == [4, 5, 6, 7]
        # post-shrink work ran on the surviving half: blocks of <= 4 chips
        resumed = [c for c in self.last_calls(tech) if c[2] > 0]
        assert resumed and all(size <= 4 for _, size, _ in resumed)

    @staticmethod
    def last_calls(tech):
        with tech.lock:
            return list(tech.calls)

    def test_preemption_does_not_consume_retry_budget(self, tmp_path):
        """A task preempted twice still has its full retry budget."""
        base = topo(8)
        mon = FleetHealthMonitor.for_topology(base)
        tech = CheckpointingTech(str(tmp_path), mon, per_batch=0.01)
        tasks = [FakeTask("solo", 40, [2, 4], tech, pbt=0.01)]
        fi = FaultInjector(schedule=[
            FaultEvent(0, FaultKind.DEVICE_LOSS, devices=(4,), after_s=0.1),
            FaultEvent(1, FaultKind.DEVICE_LOSS, devices=(5,), after_s=0.1),
        ])
        mpath = str(tmp_path / "m.jsonl")
        out = orchestrate(
            tasks, interval=0.4, topology=base, fault_injector=fi,
            health_monitor=mon, failure_policy="retry", max_task_retries=0,
            metrics_path=mpath,
        )
        assert out["completed"] == ["solo"] and out["failed"] == {}
        events = read_events(mpath)
        assert not [e for e in events if e["kind"] == "task_retry"]

    def test_injected_trial_crash_retries(self, tmp_path):
        """A scheduled transient crash flows through the ordinary retry
        path (counts against the budget — unlike preemption)."""
        tech = RecordingTech(per_batch=0.005)
        tasks = [FakeTask("crashy", 20, [4, 8], tech, pbt=0.005)]
        fi = FaultInjector(schedule=[
            FaultEvent(0, FaultKind.TRIAL_CRASH, task="crashy"),
        ])
        mpath = str(tmp_path / "m.jsonl")
        out = orchestrate(
            tasks, interval=0.5, topology=topo(8), fault_injector=fi,
            failure_policy="retry", metrics_path=mpath,
        )
        assert out["completed"] == ["crashy"] and out["failed"] == {}
        retries = read_events(mpath, kind="task_retry")
        assert len(retries) == 1 and "injected transient" in retries[0]["error"]

    def test_env_var_schedule_drives_run(self, tmp_path, monkeypatch):
        """SATURN_TPU_FAULTS alone (no injector argument) goes elastic."""
        monkeypatch.setenv("SATURN_TPU_FAULTS", "0+0.05:slice_preemption:4-7")
        tech = RecordingTech(per_batch=0.01)
        tasks = [FakeTask(f"e{i}", 30, [2, 4], tech, pbt=0.01) for i in range(2)]
        mpath = str(tmp_path / "m.jsonl")
        out = orchestrate(
            tasks, interval=0.15, topology=topo(8),
            failure_policy="retry", metrics_path=mpath,
        )
        assert sorted(out["completed"]) == ["e0", "e1"]
        assert read_events(mpath, kind="topology_change")

    def test_seeded_chaos_run_completes(self, tmp_path):
        """Fast seeded smoke: a random-but-reproducible fault schedule must
        never lose work (preempted tasks requeue, crashes retry)."""
        base = topo(8)
        mon = FleetHealthMonitor.for_topology(base)
        tech = CheckpointingTech(str(tmp_path), mon, per_batch=0.005)
        tasks = [FakeTask(f"s{i}", 30, [1, 2, 4], tech, pbt=0.005)
                 for i in range(2)]
        fi = FaultInjector(
            schedule=seeded_schedule(11, n_intervals=4, n_devices=8,
                                     p_preempt=0.6, p_crash=0.3)
        )
        out = orchestrate(
            tasks, interval=0.3, topology=base, fault_injector=fi,
            health_monitor=mon, failure_policy="retry", max_task_retries=3,
        )
        assert sorted(out["completed"]) == ["s0", "s1"]
        assert out["failed"] == {}

    def test_multihost_refuses_elastic(self, monkeypatch):
        from saturn_tpu.core import distributed

        monkeypatch.setattr(distributed, "is_multihost", lambda: True)
        tech = RecordingTech()
        tasks = [FakeTask("a", 5, [4], tech)]
        with pytest.raises(ValueError, match="single-host only"):
            orchestrate(
                tasks, topology=topo(8),
                health_monitor=FleetHealthMonitor(8),
            )


class CountingFlakyTech(BaseTechnique):
    """Fails the first ``fail_times`` execute calls per task, then succeeds;
    records every attempt so retry accounting can be asserted exactly."""

    name = "counting-flaky"

    def __init__(self, fail_times, per_batch=0.002):
        self.fail_times = fail_times
        self.per_batch = per_batch
        self.attempts = {}
        self.lock = threading.Lock()

    def execute(self, task, devices, tid, override_batch_count=None):
        with self.lock:
            self.attempts[task.name] = self.attempts.get(task.name, 0) + 1
            n = self.attempts[task.name]
        if n <= self.fail_times:
            raise RuntimeError(f"flaky failure {n} for {task.name}")
        time.sleep(self.per_batch * (override_batch_count or 1))

    def search(self, task, devices, tid):
        return {}, self.per_batch


class TestRetryAccounting:
    """failure_policy='retry' bookkeeping, exact to the attempt."""

    def test_success_on_final_allowed_attempt(self, tmp_path):
        """fail, fail, succeed with max_task_retries=2: completed, not
        failed — and both retries are visible in the metrics stream."""
        tech = CountingFlakyTech(fail_times=2)
        t = FakeTask("phoenix", 10, [8], tech, pbt=0.002)
        mpath = str(tmp_path / "m.jsonl")
        out = orchestrate(
            [t], interval=0.5, topology=topo(8), failure_policy="retry",
            max_task_retries=2, metrics_path=mpath,
        )
        assert out["completed"] == ["phoenix"]
        assert out["failed"] == {}
        assert tech.attempts["phoenix"] == 3  # 1 + exactly max_task_retries
        events = read_events(mpath)
        assert sum(e["kind"] == "task_retry" for e in events) == 2
        assert not [e for e in events if e["kind"] == "task_failed"]

    def test_budget_honored_exactly(self, tmp_path):
        """A task failing one past the budget is evicted after exactly
        1 + max_task_retries attempts — no extra interval is spent."""
        tech = CountingFlakyTech(fail_times=99)
        t = FakeTask("doomed", 10, [8], tech, pbt=0.002)
        mpath = str(tmp_path / "m.jsonl")
        out = orchestrate(
            [t], interval=0.5, topology=topo(8), failure_policy="retry",
            max_task_retries=2, metrics_path=mpath,
        )
        assert out["completed"] == []
        assert "doomed" in out["failed"]
        assert tech.attempts["doomed"] == 3
        events = read_events(mpath)
        assert sum(e["kind"] == "task_retry" for e in events) == 2
        assert sum(e["kind"] == "task_failed" for e in events) == 1

    def test_zero_retries_is_drop(self, tmp_path):
        tech = CountingFlakyTech(fail_times=99)
        t = FakeTask("oneshot", 10, [8], tech, pbt=0.002)
        out = orchestrate(
            [t], interval=0.5, topology=topo(8), failure_policy="retry",
            max_task_retries=0,
        )
        assert "oneshot" in out["failed"] and tech.attempts["oneshot"] == 1
