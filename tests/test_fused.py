"""Fused multi-model stacking (round 21): the stacked-program runtime, the
solver's measured-cost fusion pricing, the unfuse transition, and the
supporting surfaces (stacking algebra, prefetcher shape contract, plan
verifier diagnostics, memlens residency gate, fused trial profiling).

The tentpole claim mirrors rounds 10/11's trajectory-equivalence bar:
training N compatible sweep jobs as ONE compiled SPMD program (params and
optimizer state stacked along a leading ``model`` axis, the step vmapped
over it, per-member LR as a stacked array) is a pure dispatch-packing
change — every member's loss/checkpoint trajectory is bit-identical to its
solo run, through unfuse-and-resume and through a kill inside the unfuse
transition.
"""

import os

import numpy as np
import pytest

import jax

from saturn_tpu import HParams, Task
from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.data.lm_dataset import make_lm_dataset
from saturn_tpu.models.gpt2 import build_gpt2
from saturn_tpu.models.loss import pretraining_loss
from saturn_tpu.ops import stacking
from saturn_tpu.parallel import fused
from saturn_tpu.parallel.dp import DataParallel
from saturn_tpu.solver import milp
from saturn_tpu.solver.milp import Assignment, Plan
from saturn_tpu.utils import checkpoint as ckpt

pytestmark = pytest.mark.fused

SEQ = 16
BATCH = 2
VOCAB = 64
N_BATCHES = 6
SWEEP_LRS = {"a": 1e-3, "b": 2e-3, "c": 5e-4}


# --------------------------------------------------------------- fakes
class FakeDev:
    platform = "cpu"
    device_kind = "fake-cpu"
    process_index = 0


def fake_topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class FakeTask:
    """Solver-facing task: name + per-size strategy table."""

    def __init__(self, name, sizes, runtime=10.0, pbt=0.1, fused_pbt=None):
        self.name = name
        self.strategies = {
            g: Strategy(object(), g, {}, runtime, pbt,
                        fused_per_batch_time=fused_pbt)
            for g in sizes
        }

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}


# --------------------------------------------------------------- real tasks
def make_member(save_dir: str, name: str, lr: float,
                batch_count: int = N_BATCHES) -> Task:
    t = Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=SEQ, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=SEQ, batch_size=BATCH, vocab_size=VOCAB,
            n_tokens=SEQ * BATCH * 16,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=lr, batch_count=batch_count),
        chip_range=[1],
        name=name,
        save_dir=save_dir,
    )
    t.strategies[1] = Strategy(executor=DataParallel(), apportionment=1,
                               params={}, runtime=1.0, per_batch_time=0.01)
    t.select_strategy(1)
    return t


@pytest.fixture(scope="module")
def solo_refs(tmp_path_factory):
    """Uninterrupted solo runs of the sweep configs — the bit-identity
    reference every fused/unfused trajectory must reproduce."""
    root = tmp_path_factory.mktemp("solo_refs")
    tech = DataParallel()
    devs = jax.devices()[:1]
    states = {}
    for key, lr in SWEEP_LRS.items():
        t = make_member(str(root / key), f"solo-{key}", lr)
        tech.execute(t, devs, 0, override_batch_count=N_BATCHES)
        ckpt.flush()
        states[key] = ckpt.load_arrays(t.ckpt_path)
    return states


def assert_states_equal(got: dict, want: dict, who: str) -> None:
    assert set(got) == set(want), who
    for k in sorted(want):
        assert np.array_equal(got[k], want[k]), f"{who}: leaf {k} diverged"


# =================================================================== stacking
class TestStacking:
    def _tree(self, seed):
        rng = np.random.default_rng(seed)
        return {"w": rng.normal(size=(3, 4)).astype(np.float32),
                "b": rng.normal(size=(4,)).astype(np.float32)}

    def test_stack_unstack_roundtrip(self):
        trees = [self._tree(i) for i in range(3)]
        stacked = stacking.stack_trees(trees)
        assert stacked["w"].shape == (3, 3, 4)
        back = stacking.unstack_tree(stacked, 3)
        for orig, got in zip(trees, back):
            assert_states_equal(got, orig, "roundtrip")

    def test_member_slice_is_checkpoint_view(self):
        trees = [self._tree(i) for i in range(3)]
        stacked = stacking.stack_trees(trees)
        assert_states_equal(stacking.member_slice(stacked, 1), trees[1],
                            "member 1")

    def test_remove_member_preserves_order(self):
        trees = [self._tree(i) for i in range(4)]
        shrunk = stacking.remove_member(stacking.stack_trees(trees), 1)
        assert shrunk["w"].shape[0] == 3
        for out_i, src_i in enumerate([0, 2, 3]):
            assert_states_equal(stacking.member_slice(shrunk, out_i),
                                trees[src_i], f"survivor {src_i}")

    def test_batch_mismatch_names_the_member(self):
        good = np.zeros((2, 8), dtype=np.int32)
        bad = np.zeros((2, 9), dtype=np.int32)
        with pytest.raises(stacking.MemberShapeError) as ei:
            stacking.stack_member_batches(
                [good, bad, good], member_names=["a", "b", "c"])
        assert "b" in str(ei.value)


# ================================================== fingerprint / candidates
class TestFusionFingerprint:
    def test_lr_rides_along(self, tmp_path):
        a = make_member(str(tmp_path / "a"), "fa", 1e-3)
        b = make_member(str(tmp_path / "b"), "fb", 7e-3)
        fp_a, fp_b = fused.fusion_fingerprint(a), fused.fusion_fingerprint(b)
        assert fp_a is not None and fp_a == fp_b

    def test_callable_optimizer_cannot_fuse(self, tmp_path):
        t = make_member(str(tmp_path / "t"), "ft", 1e-3)
        t.hparams.optimizer = lambda lr: None
        t._fusion_fingerprint = False  # drop the cached value
        assert fused.fusion_fingerprint(t) is None

    def test_candidates_group_and_chunk(self, tmp_path):
        tasks = [make_member(str(tmp_path / f"m{i}"), f"m{i}", 1e-3 * (i + 1))
                 for i in range(5)]
        groups = fused.fusion_candidates(tasks, max_members=3)
        assert sorted(n for g in groups for n in g) == [
            f"m{i}" for i in range(5)
        ]
        assert all(2 <= len(g) <= 3 for g in groups)


# ==================================================================== plan
class TestPlanFusedWire:
    def _plan(self):
        return Plan(
            assignments={
                "a": Assignment(1, Block(0, 1), 0.0, 1.0),
                "b": Assignment(1, Block(0, 1), 0.0, 1.0),
                "c": Assignment(1, Block(4, 1), 0.0, 1.0),
            },
            makespan=1.0,
            fused=[["a", "b"]],
        )

    def test_json_roundtrip(self):
        plan = self._plan()
        back = Plan.from_json(plan.to_json())
        assert back.fused == [["a", "b"]]
        assert back.fused_group_of() == {"a": 0, "b": 0}

    def test_from_json_backcompat(self):
        d = self._plan().to_json()
        del d["fused"]
        assert Plan.from_json(d).fused == []

    def test_dependencies_exempt_fused_members(self):
        plan = self._plan()
        plan.compute_dependencies()
        # a and b share Block(0,1) at the same start but are one stack:
        # no ordering edge between them
        assert plan.dependencies["a"] == []
        assert plan.dependencies["b"] == []

    def test_verifier_exempts_fused_overlap(self):
        from saturn_tpu.analysis import plan_verifier

        report = plan_verifier.verify_plan(self._plan(), topology=fake_topo())
        assert not [d for d in report.errors if d.code == "SAT-P001"]

    def test_verifier_flags_divergent_fused_slots(self):
        from saturn_tpu.analysis import plan_verifier

        plan = Plan(
            assignments={
                "a": Assignment(1, Block(0, 1), 0.0, 1.0),
                "b": Assignment(1, Block(1, 1), 0.0, 1.0),
            },
            makespan=1.0,
            fused=[["a", "b"]],
        )
        report = plan_verifier.verify_plan(plan, topology=fake_topo())
        assert [d for d in report.errors if d.code == "SAT-P025"]

    def test_verifier_warns_on_unpriced_fusion(self):
        from saturn_tpu.analysis import plan_verifier

        plan = self._plan()
        tasks = [FakeTask("a", [1]), FakeTask("b", [1], fused_pbt=0.05)]
        report = plan_verifier.verify_plan(plan, topology=fake_topo(),
                                           tasks=tasks)
        warned = [d for d in report.diagnostics if d.code == "SAT-P026"]
        assert [d.counterexample["task"] for d in warned] == ["a"]


# ================================================================= pricing
class TestFusionPricing:
    def test_fuses_when_measured_cost_wins(self):
        tasks = [FakeTask(n, [1, 2], runtime=10.0, pbt=0.1, fused_pbt=0.12)
                 for n in ("a", "b", "c")]
        priced = milp.fusion_priced_groups(
            tasks, [["a", "b", "c"]], fake_topo())
        assert len(priced) == 1
        names, size, fused_rt, fpbt = priced[0]
        assert names == ["a", "b", "c"]
        # 100 remaining batches x 0.12 s lockstep = 12 s vs 30 s serial
        assert fused_rt == pytest.approx(12.0)
        assert fpbt == pytest.approx(0.12)

    def test_never_fuses_on_guesswork(self):
        # fused_per_batch_time=None at every size: no measured lockstep cost
        tasks = [FakeTask(n, [1, 2]) for n in ("a", "b")]
        assert milp.fusion_priced_groups(tasks, [["a", "b"]],
                                         fake_topo()) == []

    def test_fuses_nothing_when_slower_than_serial(self):
        # lockstep step 10x a solo batch: serial wins, group refused
        tasks = [FakeTask(n, [1], runtime=10.0, pbt=0.1, fused_pbt=1.0)
                 for n in ("a", "b")]
        assert milp.fusion_priced_groups(tasks, [["a", "b"]],
                                         fake_topo()) == []

    def test_memlens_gate_vetoes(self):
        tasks = [FakeTask(n, [1], runtime=10.0, pbt=0.1, fused_pbt=0.12)
                 for n in ("a", "b")]
        vetoed = milp.fusion_priced_groups(
            tasks, [["a", "b"]], fake_topo(),
            fusion_fits=lambda members, size, n: False)
        assert vetoed == []
        unknown = milp.fusion_priced_groups(
            tasks, [["a", "b"]], fake_topo(),
            fusion_fits=lambda members, size, n: None)
        assert len(unknown) == 1

    def test_exclude_shrinks_group(self):
        tasks = [FakeTask(n, [1], runtime=10.0, pbt=0.1, fused_pbt=0.12)
                 for n in ("a", "b", "c")]
        priced = milp.fusion_priced_groups(
            tasks, [["a", "b", "c"]], fake_topo(), fusion_exclude={"b"})
        assert priced and priced[0][0] == ["a", "c"]

    def test_solve_emits_fused_plan_with_identical_slots(self):
        tasks = [FakeTask(n, [1, 2], runtime=10.0, pbt=0.1, fused_pbt=0.12)
                 for n in ("a", "b", "c")]
        plan = milp.solve(tasks, fake_topo(), fusion=[["a", "b", "c"]])
        assert plan.fused == [["a", "b", "c"]]
        slots = {
            (a.apportionment, a.block.offset, a.block.size, a.start)
            for n, a in plan.assignments.items() if n in {"a", "b", "c"}
        }
        assert len(slots) == 1
        from saturn_tpu.analysis import plan_verifier

        report = plan_verifier.verify_plan(plan, topology=fake_topo())
        assert report.ok, [d.message for d in report.errors]

    def test_solve_falls_back_solo_when_unpriced(self):
        tasks = [FakeTask(n, [1, 2], runtime=10.0, pbt=0.1)
                 for n in ("a", "b", "c")]
        plan = milp.solve(tasks, fake_topo(), fusion=[["a", "b", "c"]])
        assert plan.fused == []


# ============================================================== trajectories
class TestFusedTrajectory:
    def test_fused_members_match_solo_bitwise(self, tmp_path, solo_refs):
        members = [
            make_member(str(tmp_path / k), f"tr-{k}", lr)
            for k, lr in SWEEP_LRS.items()
        ]
        report = fused.run_fused_interval(
            members, jax.devices()[:1], 0,
            batch_counts=[N_BATCHES] * len(members))
        ckpt.flush()
        assert report.n_steps == N_BATCHES
        for t, key in zip(members, SWEEP_LRS):
            mr = report.members[t.name]
            assert mr.steps == N_BATCHES and mr.fault is None
            assert_states_equal(ckpt.load_arrays(t.ckpt_path),
                                solo_refs[key], t.name)

    def test_sharded_model_axis_matches_solo(self, tmp_path, solo_refs):
        lrs = [SWEEP_LRS["a"], SWEEP_LRS["b"], SWEEP_LRS["c"], 3e-3]
        members = [
            make_member(str(tmp_path / f"s{i}"), f"sh-{i}", lr)
            for i, lr in enumerate(lrs)
        ]
        fused.run_fused_interval(members, jax.devices()[:2], 0,
                                 batch_counts=[N_BATCHES] * 4)
        ckpt.flush()
        assert_states_equal(ckpt.load_arrays(members[0].ckpt_path),
                            solo_refs["a"], "sharded member 0")

    def test_unfuse_and_solo_resume_is_exact(self, tmp_path, solo_refs):
        members = [
            make_member(str(tmp_path / k), f"uf-{k}", lr)
            for k, lr in SWEEP_LRS.items()
        ]
        polls = {"n": 0}

        def detach_b_at_second_boundary(t):
            if t.name != "uf-b":
                return False
            polls["n"] += 1
            return polls["n"] > 1

        report = fused.run_fused_interval(
            members, jax.devices()[:1], 0,
            batch_counts=[N_BATCHES] * 3, window_size=2,
            detach_requested=detach_b_at_second_boundary)
        ckpt.flush()
        assert len(report.detached) == 1
        detached, steps_done = report.detached[0]
        assert detached.name == "uf-b" and 0 < steps_done < N_BATCHES
        assert report.members["uf-b"].detached_at == steps_done
        # solo resume for the remaining batches restores the exact
        # uninterrupted-solo trajectory
        tech = detached.strategies[1].executor
        tech.execute(detached, jax.devices()[:1], 0,
                     override_batch_count=N_BATCHES - steps_done)
        ckpt.flush()
        assert_states_equal(ckpt.load_arrays(detached.ckpt_path),
                            solo_refs["b"], "unfused b")
        for t, key in [(members[0], "a"), (members[2], "c")]:
            assert report.members[t.name].steps == N_BATCHES
            assert_states_equal(ckpt.load_arrays(t.ckpt_path),
                                solo_refs[key], f"survivor {key}")


# ============================================================ crash replay
@pytest.mark.crash
class TestUnfuseCrashReplay:
    def test_kill_inside_unfuse_replays_exactly_once(
            self, tmp_path, solo_refs):
        """SimulatedKill at the ``fused.unfuse`` barrier: the barrier fires
        BEFORE the detached member's checkpoint lands, so the kill leaves
        nothing durable from the interval — replay re-runs it bit-
        identically, unfuses at the same boundary, and the detached member's
        solo resume lands exactly on the uninterrupted-solo trajectory (no
        lost, no duplicated steps)."""
        from saturn_tpu.resilience import CrashInjector, SimulatedKill

        members = [
            make_member(str(tmp_path / k), f"cr-{k}", lr)
            for k, lr in SWEEP_LRS.items()
        ]

        def make_detach():
            polls = {"n": 0}

            def cb(t):
                if t.name != "cr-b":
                    return False
                polls["n"] += 1
                return polls["n"] > 1

            return cb

        inj = CrashInjector("fused.unfuse", hit=1)
        ckpt.set_crash_barrier(inj.barrier)
        try:
            with pytest.raises(SimulatedKill):
                fused.run_fused_interval(
                    members, jax.devices()[:1], 0,
                    batch_counts=[N_BATCHES] * 3, window_size=2,
                    detach_requested=make_detach())
            ckpt.flush()
            # nothing durable for the detached member: the kill preceded
            # its checkpoint save
            assert not os.path.exists(members[1].ckpt_path)
        finally:
            ckpt.set_crash_barrier(None)

        # replay: the next incarnation re-runs the interval from scratch
        # (fresh task objects, same configs — nothing was durable)
        replay = [
            make_member(str(tmp_path / k), f"cr-{k}", lr)
            for k, lr in SWEEP_LRS.items()
        ]
        report = fused.run_fused_interval(
            replay, jax.devices()[:1], 0,
            batch_counts=[N_BATCHES] * 3, window_size=2,
            detach_requested=make_detach())
        ckpt.flush()
        detached, steps_done = report.detached[0]
        assert detached.name == "cr-b"
        tech = detached.strategies[1].executor
        tech.execute(detached, jax.devices()[:1], 0,
                     override_batch_count=N_BATCHES - steps_done)
        ckpt.flush()
        final = ckpt.load_arrays(detached.ckpt_path)
        assert_states_equal(final, solo_refs["b"], "replayed b")
        assert int(final["step"]) == N_BATCHES  # exactly once
        for t, key in [(replay[0], "a"), (replay[2], "c")]:
            assert_states_equal(ckpt.load_arrays(t.ckpt_path),
                                solo_refs[key], f"replay survivor {key}")


# ================================================================ engine
class TestEngineFusedLaunch:
    def test_engine_runs_fused_group_end_to_end(self, tmp_path, solo_refs):
        from saturn_tpu.executor import engine

        members = [
            make_member(str(tmp_path / k), f"en-{k}", lr)
            for k, lr in SWEEP_LRS.items()
        ]
        plan = Plan(
            assignments={
                t.name: Assignment(1, Block(0, 1), 0.0, 1.0)
                for t in members
            },
            makespan=1.0,
            fused=[[t.name for t in members]],
        )
        plan.compute_dependencies()
        topo = SliceTopology(jax.devices())
        errors = engine.execute(
            members, {t.name: N_BATCHES for t in members}, 120.0, plan, topo)
        ckpt.flush()
        assert errors == {}
        for t, key in zip(members, SWEEP_LRS):
            assert t.current_batch == N_BATCHES  # cursor advanced once
            # realized lockstep cost fed back for the solver's next pricing
            assert t.strategies[1].fused_per_batch_time is not None
            assert_states_equal(ckpt.load_arrays(t.ckpt_path),
                                solo_refs[key], f"engine {key}")


# ============================================================== trial runner
class TestProfileFusedGroup:
    def test_measures_and_installs_lockstep_cost(self, tmp_path):
        from saturn_tpu.trial_runner import evaluator

        members = [
            make_member(str(tmp_path / f"p{i}"), f"pf-{i}", 1e-3 * (i + 1))
            for i in range(2)
        ]
        topo = SliceTopology(jax.devices()[:1])
        measured = evaluator.profile_fused_group(
            members, topology=topo, steps=2, warmup=1)
        assert 1 in measured and measured[1] > 0.0
        for t in members:
            assert t.strategies[1].fused_per_batch_time == measured[1]
        # pure measurement: no cursor movement, no checkpoint
        for t in members:
            assert t.current_batch == 0
            assert not os.path.exists(t.ckpt_path)

    def test_rejects_unfusable_group(self, tmp_path):
        from saturn_tpu.trial_runner import evaluator

        a = make_member(str(tmp_path / "a"), "rx-a", 1e-3)
        b = make_member(str(tmp_path / "b"), "rx-b", 1e-3)
        b.hparams.optimizer = lambda lr: None  # unfingerprintable
        b._fusion_fingerprint = False
        with pytest.raises(ValueError):
            evaluator.profile_fused_group(
                [a, b], topology=SliceTopology(jax.devices()[:1]))


# ================================================================ prefetch
class TestStackedShapeContract:
    def test_prefetcher_blames_the_group(self):
        from saturn_tpu.data.prefetch import DevicePrefetcher, ShapeContractError

        good = np.zeros((3, 2, 8), dtype=np.int32)
        bad = np.zeros((2, 2, 8), dtype=np.int32)
        pf = DevicePrefetcher(
            2, lambda i: good if i == 0 else bad,
            expect_shapes=[(3, 2, 8)], member_names=["a", "b", "c"])
        try:
            assert next(pf) is good
            with pytest.raises(ShapeContractError) as ei:
                next(pf)
        finally:
            pf.close()
        msg = str(ei.value)
        assert "(2, 2, 8)" in msg and "a" in msg
        assert ei.value.member_names == ["a", "b", "c"]


# ================================================================= memlens
class TestFusedStackFits:
    def test_unknown_without_capacity(self):
        from saturn_tpu.analysis.memlens import passes as ml_passes

        verdict = ml_passes.fused_stack_fits(
            object(), object(), [FakeDev()], 4, capacity_bytes=0)
        assert verdict is None

    def test_fits_and_vetoes_on_real_trace(self, tmp_path):
        from saturn_tpu.analysis.memlens import passes as ml_passes

        t = make_member(str(tmp_path / "m"), "ml-m", 1e-3)
        tech = DataParallel()
        devs = jax.devices()[:1]
        roomy = ml_passes.fused_stack_fits(
            tech, t, devs, 4, capacity_bytes=1 << 40)
        tight = ml_passes.fused_stack_fits(
            tech, t, devs, 4, capacity_bytes=1 << 10)
        assert roomy is True
        assert tight is False
