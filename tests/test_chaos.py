"""Chaos campaign harness (round 13): seeded schedules, row schema, and the
ISSUE's acceptance sweep.

The fast half is hardware-free: schedule determinism/coverage, the
``compare_checkpoints`` bit-identity primitive, and the benchmark row schema
guard. The slow half runs the real acceptance campaign — three seeded
mixed-fault sweeps (one per health-fault class each) over two tiny GPT-2
jobs, the first seed killed at the ``post-rollback`` journal barrier — and
asserts zero lost jobs, quarantine surviving the kill via journal replay,
and byte-identical final checkpoints against a fault-free reference run
with the campaign's quarantine pre-applied.
"""

import importlib.util
import os

import numpy as np
import pytest

from saturn_tpu.resilience.chaos import (
    CampaignSpec,
    HEALTH_FAULT_CLASSES,
    campaign_schedule,
    compare_checkpoints,
    run_campaign,
)
from saturn_tpu.resilience.faults import FaultKind

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_guard():
    spec = importlib.util.spec_from_file_location(
        "bench_guard_chaos", os.path.join(REPO, "benchmarks", "bench_guard.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


# ----------------------------------------------------------------- schedule
class TestCampaignSchedule:
    NAMES = ["job-a", "job-b", "job-c"]

    def test_deterministic_for_a_seed(self):
        spec = CampaignSpec(seed=7)
        assert campaign_schedule(self.NAMES, spec) == \
            campaign_schedule(self.NAMES, spec)
        other = campaign_schedule(self.NAMES, CampaignSpec(seed=8))
        assert other != campaign_schedule(self.NAMES, spec)

    def test_one_event_per_health_class(self):
        events = campaign_schedule(self.NAMES, CampaignSpec(seed=3))
        assert [e.kind for e in events] == list(HEALTH_FAULT_CLASSES)
        for e in events:
            assert e.task in self.NAMES
            assert e.at_interval == 0  # max_intervals_hit defaults to 1

    def test_event_payload_by_class(self):
        spec = CampaignSpec(seed=5, poison_range=6, poison_batches=2,
                            stall_s=0.7)
        by_kind = {e.kind: e for e in campaign_schedule(self.NAMES, spec)}
        poison = by_kind[FaultKind.BATCH_POISON]
        assert len(poison.batches) == 2
        assert all(0 <= i < 6 for i in poison.batches)
        assert by_kind[FaultKind.DISPATCH_STALL].stall_s == 0.7
        assert 0 <= by_kind[FaultKind.NUMERIC_NAN].step < 4

    def test_non_health_class_rejected(self):
        spec = CampaignSpec(seed=1, fault_classes=(FaultKind.DEVICE_LOSS,))
        with pytest.raises(ValueError, match="not a health-fault class"):
            campaign_schedule(self.NAMES, spec)

    def test_empty_task_list_rejected(self):
        with pytest.raises(ValueError, match="at least one task"):
            campaign_schedule([], CampaignSpec(seed=1))


# -------------------------------------------------------- compare primitive
class TestCompareCheckpoints:
    def _save(self, d, stem, **arrays):
        os.makedirs(d, exist_ok=True)
        np.savez(os.path.join(d, f"{stem}.npz"), **arrays)

    def test_identical_including_nan(self, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        w = np.asarray([1.0, np.nan, 3.0], dtype=np.float32)
        self._save(a, "job", w=w, b=np.zeros(2))
        self._save(b, "job", w=w.copy(), b=np.zeros(2))
        assert compare_checkpoints(a, b) == []

    def test_single_bit_flip_caught(self, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        w = np.arange(4, dtype=np.float32)
        self._save(a, "job", w=w)
        w2 = w.copy()
        w2.view(np.uint32)[1] ^= 1  # flip one mantissa bit
        self._save(b, "job", w=w2)
        assert compare_checkpoints(a, b) == ["job[w]: bytes differ"]

    def test_missing_and_key_mismatch(self, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        self._save(a, "job", w=np.zeros(2))
        self._save(a, "gone", w=np.zeros(2))
        self._save(b, "job", other=np.zeros(2))
        got = compare_checkpoints(a, b)
        assert any("gone: missing" in m for m in got)
        assert any("key sets differ" in m for m in got)

    def test_explicit_names_limit_the_comparison(self, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        self._save(a, "job", w=np.zeros(2))
        self._save(a, "junk", w=np.ones(2))
        self._save(b, "job", w=np.zeros(2))
        assert compare_checkpoints(a, b, names=["job"]) == []


# ---------------------------------------------------------------- row schema
class TestChaosRowSchema:
    GOOD = {
        "metric": "chaos_campaign",
        "seeds": [11, 23, 47],
        "fault_classes": ["numeric_nan", "loss_spike", "batch_poison",
                          "dispatch_stall"],
        "jobs": 6,
        "jobs_lost": 0,
        "restarts": 1,
        "quarantined_batches": 3,
        "makespan_inflation": 1.2,
        "trajectory_bit_identical": True,
        "sentinel_overhead_pct": 0.4,
        "platform": "cpu",
        "status": "ok",
    }

    def test_good_row_passes(self):
        assert _bench_guard().validate_chaos_row(dict(self.GOOD)) == []

    def test_missing_key_flagged(self):
        row = dict(self.GOOD)
        del row["jobs_lost"]
        assert any("jobs_lost" in p for p in
                   _bench_guard().validate_chaos_row(row))

    def test_bool_in_count_field_flagged(self):
        row = dict(self.GOOD, jobs_lost=False)
        assert any("is bool" in p for p in
                   _bench_guard().validate_chaos_row(row))

    def test_too_few_seeds_or_classes_flagged(self):
        m = _bench_guard()
        assert any("fewer than 3 seeds" in p for p in
                   m.validate_chaos_row(dict(self.GOOD, seeds=[1, 2])))
        assert any(
            "fewer than 4 fault classes" in p for p in
            m.validate_chaos_row(
                dict(self.GOOD, fault_classes=["numeric_nan"])
            )
        )

    def test_non_dict_rejected(self):
        assert _bench_guard().validate_chaos_row([1, 2]) != []


# --------------------------------------------------------------- acceptance
SEQ_LEN = 16
BATCH_SIZE = 2
N_BATCHES = 8   # == epoch length, so quarantine comparison stays exact
TASK_NAMES = ("chaos-a", "chaos-b")


def _make_template(save_dir, name):
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    return Task(
        get_model=lambda **kw: build_gpt2("test-tiny", seq_len=SEQ_LEN, **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=SEQ_LEN, batch_size=BATCH_SIZE, vocab_size=256,
            n_tokens=SEQ_LEN * BATCH_SIZE * N_BATCHES,
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=N_BATCHES),
        chip_range=[2],
        name=name,
        save_dir=save_dir,
    )


def _clone_tasks(templates, save_dir):
    os.makedirs(save_dir, exist_ok=True)
    out = []
    for t in templates:
        c = t.clone(name=t.name)
        c.save_dir = save_dir
        out.append(c)
    return out


@pytest.mark.slow
class TestAcceptanceCampaign:
    """The ISSUE's scenario: >= 4 fault classes x >= 3 seeds, one seed killed
    mid-recovery, zero lost jobs, quarantine surviving the kill, and
    bit-identical post-rollback trajectories."""

    SEEDS = (11, 23, 47)

    @pytest.fixture(scope="class")
    def profiled_templates(self, tmp_path_factory):
        import jax

        import saturn_tpu
        from saturn_tpu import library
        from saturn_tpu.core.mesh import SliceTopology
        from saturn_tpu.health import SentinelConfig, sentinel

        library.register_default_library()
        # The campaign injects 1e9 spikes; the EWMA screen (off by default —
        # divergence thresholds are workload policy) must be on to see them.
        sentinel.set_config(
            SentinelConfig(enabled=True, spike_factor=8.0, warmup_steps=2)
        )
        tmp = tmp_path_factory.mktemp("chaos-acceptance")
        templates = [
            _make_template(str(tmp / "templates"), n) for n in TASK_NAMES
        ]
        topo = SliceTopology(jax.devices())
        saturn_tpu.search(templates, technique_names=["dp"], topology=topo)
        yield templates, topo, tmp
        sentinel.set_config(None)

    def test_campaign_sweep(self, profiled_templates):
        import saturn_tpu
        from saturn_tpu.durability import replay_batch_state

        templates, topo, tmp = profiled_templates
        orchestrate_kw = dict(interval=30.0, topology=topo,
                              solver_time_limit=2.0)
        kills = 0
        for i, seed in enumerate(self.SEEDS):
            spec = CampaignSpec(seed=seed, kill_during_rollback=(i == 0),
                                poison_range=N_BATCHES, stall_s=0.25)
            save = str(tmp / f"camp{seed}" / "ckpts")
            wal = str(tmp / f"camp{seed}" / "wal")
            result = run_campaign(
                lambda: _clone_tasks(templates, save), spec, wal,
                **orchestrate_kw,
            )

            # zero lost jobs, across every restart
            assert sorted(result.completed) == sorted(TASK_NAMES)
            assert result.failed == {}
            kills += result.kills

            # quarantine survived: what the harness reports IS what a fresh
            # incarnation would replay out of the durable journal
            assert result.quarantined == replay_batch_state(wal).quarantined

            # bit-identical trajectory: a fault-free run over the same
            # surviving batch sequence produces the same bytes
            ref_save = str(tmp / f"camp{seed}" / "ref")
            ref_tasks = _clone_tasks(templates, ref_save)
            for t in ref_tasks:
                t.quarantine_batches(result.quarantined.get(t.name, []))
            saturn_tpu.orchestrate(ref_tasks, **orchestrate_kw)
            assert compare_checkpoints(save, ref_save,
                                       names=list(TASK_NAMES)) == []

        # the armed seed really did die at post-rollback and restart
        assert kills >= 1

    def test_stall_below_watchdog_deadline_is_absorbed(self, profiled_templates):
        """A dispatch stall shorter than the watchdog deadline costs wall
        clock only — no fault, no restart, jobs complete first try."""
        from saturn_tpu.resilience.faults import FaultEvent, FaultInjector

        import saturn_tpu

        templates, topo, tmp = profiled_templates
        tasks = _clone_tasks(templates, str(tmp / "stall" / "ckpts"))
        injector = FaultInjector(schedule=[
            FaultEvent(0, FaultKind.DISPATCH_STALL, task="chaos-a",
                       stall_s=0.2),
        ])
        out = saturn_tpu.orchestrate(
            tasks, interval=30.0, topology=topo, solver_time_limit=2.0,
            fault_injector=injector,
        )
        assert sorted(out["completed"]) == sorted(TASK_NAMES)
        assert out["failed"] == {}
