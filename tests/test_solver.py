"""MILP solver tests on synthetic runtime tables — hardware-free, exactly the
unit-test layer SURVEY.md §4 says the reference lacks (solver consumes only
numbers, reference ``milp.py:77-81``)."""

import numpy as np
import pytest

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.solver.lp import Expr, Model
from saturn_tpu.solver.milp import (
    greedy_plan,
    makespan_lower_bound,
    resolve,
    solve,
)


class FakeDev:
    pass


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class FakeTask:
    """Solver-facing duck type: only .name and .feasible_strategies()."""

    def __init__(self, name, runtimes):
        # runtimes: {size: seconds}
        self.name = name
        self.strategies = {
            g: Strategy(object(), g, {}, rt, 0.1) for g, rt in runtimes.items()
        }

    def feasible_strategies(self):
        return self.strategies


class TestLP:
    def test_simple_milp(self):
        m = Model()
        xx = m.binary("x")
        y = m.continuous("y", lb=0, ub=10)
        m.add(Expr.of(y) >= 3 * Expr.of(xx))
        m.add(Expr.of(xx) + Expr.of(y) >= 2)
        m.minimize(Expr.of(y))
        r = m.solve()
        assert r.ok
        # x=1,y=3 costs 3; x=0,y=2 costs 2 -> optimal y=2
        assert abs(r.objective - 2.0) < 1e-6

    def test_infeasible(self):
        m = Model()
        v = m.continuous("x", lb=0, ub=1)
        m.add(Expr.of(v) >= 2)
        m.minimize(Expr.of(v))
        assert not m.solve().ok


class TestLowerBound:
    def test_lb_never_exceeds_exact_optimum(self):
        """The bound must be valid: LB <= the exactly-solved makespan on
        random small instances (where HiGHS proves optimality)."""
        rng = np.random.default_rng(5)
        for trial in range(4):
            tasks = [
                FakeTask(
                    f"lb{trial}_{i}",
                    {s: float(rng.uniform(2, 30)) for s in (1, 2, 4)},
                )
                for i in range(4)
            ]
            plan = solve(tasks, topo(8), time_limit=20.0, ordering_slack=0.0)
            lb = makespan_lower_bound(tasks, topo(8))
            assert lb <= plan.makespan + 1e-6
            assert lb > 0

    def test_lb_longest_task(self):
        # one long 1-chip-only task dominates
        tasks = [FakeTask("long", {1: 100.0}), FakeTask("short", {1: 1.0})]
        assert makespan_lower_bound(tasks, topo(8)) >= 100.0

    def test_lb_whole_ring_serialization(self):
        # both tasks can only take the full ring -> they serialize
        tasks = [FakeTask("a", {8: 10.0}), FakeTask("b", {8: 10.0})]
        assert makespan_lower_bound(tasks, topo(8)) >= 20.0 - 1e-9

    def test_lb_area(self):
        # 8 one-chip 10s tasks on 2 devices: area bound = 8*10/2 = 40
        tasks = [FakeTask(f"t{i}", {1: 10.0}) for i in range(8)]
        assert makespan_lower_bound(tasks, topo(2)) >= 40.0 - 1e-6


class TestSolve:
    def test_two_tasks_parallel(self):
        """Two 4-chip tasks on 8 chips should run concurrently on disjoint
        blocks -> makespan == max runtime, not sum."""
        t1 = FakeTask("a", {4: 100.0})
        t2 = FakeTask("b", {4: 80.0})
        plan = solve([t1, t2], topo(8))
        a, b = plan.assignments["a"], plan.assignments["b"]
        assert not a.block.overlaps(b.block)
        assert plan.makespan <= 100.0 + 1e-6
        assert plan.dependencies == {"a": [], "b": []}

    def test_contention_serializes(self):
        """Two 8-chip tasks must be time-ordered on the single block."""
        t1 = FakeTask("a", {8: 50.0})
        t2 = FakeTask("b", {8: 60.0})
        plan = solve([t1, t2], topo(8), ordering_slack=0.0)
        a, b = plan.assignments["a"], plan.assignments["b"]
        assert a.block.overlaps(b.block)
        assert plan.makespan >= 110.0 - 1e-6
        first, second = (a, b) if a.start <= b.start else (b, a)
        assert second.start >= first.start + first.runtime - 1e-6
        # dependency edge from later onto earlier
        later = "b" if second is b else "a"
        earlier = "a" if later == "b" else "b"
        assert plan.dependencies[later] == [earlier]

    def test_strategy_selection_tradeoff(self):
        """Scaling choice: two tasks each run 100s on 8 chips or 180s on 4.
        Best makespan = 180 (both on half-slice in parallel), not 200."""
        t1 = FakeTask("a", {8: 100.0, 4: 180.0})
        t2 = FakeTask("b", {8: 100.0, 4: 180.0})
        plan = solve([t1, t2], topo(8), ordering_slack=0.0)
        assert plan.makespan <= 180.0 + 1e-6
        assert plan.assignments["a"].apportionment == 4
        assert plan.assignments["b"].apportionment == 4

    def test_short_tasks_default_slack_not_infeasible(self, caplog):
        """Big-M must cover ordering_slack: short-runtime batches with the
        default slack must solve optimally, not fall back to greedy."""
        import logging

        t1 = FakeTask("a", {8: 1.0})
        t2 = FakeTask("b", {8: 1.0})
        with caplog.at_level(logging.WARNING, logger="saturn_tpu"):
            plan = solve([t1, t2], topo(8))  # default ordering_slack=1.0
        assert "falling back" not in caplog.text
        # serialized with 1s slack between: 1 + 1 + 1
        assert plan.makespan == pytest.approx(3.0, abs=1e-4)

    def test_no_feasible_strategy_raises(self):
        t = FakeTask("a", {})
        with pytest.raises(ValueError):
            solve([t], topo(8))

    def test_infeasible_sizes_skipped(self):
        """A 16-chip strategy on an 8-chip slice is ignored; 4-chip used."""
        t = FakeTask("a", {16: 10.0, 4: 50.0})
        plan = solve([t], topo(8))
        assert plan.assignments["a"].apportionment == 4

    def test_mixed_sizes_pack(self):
        """8 single-chip tasks of 10s each pack onto 8 chips: makespan 10."""
        tasks = [FakeTask(f"t{i}", {1: 10.0}) for i in range(8)]
        plan = solve(tasks, topo(8), ordering_slack=0.0)
        assert plan.makespan <= 10.0 + 1e-6
        offsets = {p.block.offset for p in plan.assignments.values()}
        assert len(offsets) == 8  # all disjoint


class TestGreedy:
    def test_greedy_matches_structure(self):
        tasks = [FakeTask(f"t{i}", {2: 30.0, 4: 20.0}) for i in range(4)]
        plan = greedy_plan(tasks, topo(8))
        assert set(plan.assignments) == {f"t{i}" for i in range(4)}
        # blocks valid & within capacity
        for a in plan.assignments.values():
            assert a.block.end <= 8
        # no two overlapping blocks overlap in time
        items = list(plan.assignments.values())
        for i, a in enumerate(items):
            for b in items[i + 1 :]:
                if a.block.overlaps(b.block):
                    assert (
                        a.start + a.runtime <= b.start + 1e-9
                        or b.start + b.runtime <= a.start + 1e-9
                    )


class TestResolve:
    def test_adopts_when_no_previous(self):
        t = FakeTask("a", {4: 100.0})
        p = resolve([t], topo(8), None, interval=10.0)
        assert "a" in p.assignments

    def test_keeps_slid_plan_when_not_better(self):
        t1 = FakeTask("a", {8: 50.0})
        t2 = FakeTask("b", {8: 60.0})
        prev = solve([t1, t2], topo(8), ordering_slack=0.0)
        p = resolve([t1, t2], topo(8), prev, interval=10.0, threshold=0.0)
        # fresh solve can't beat slid-down optimal; starts slid by interval
        for n in ("a", "b"):
            assert p.assignments[n].start == pytest.approx(
                max(0.0, prev.assignments[n].start - 10.0)
            )

    def test_adopts_on_shrink(self):
        t1 = FakeTask("a", {8: 50.0})
        t2 = FakeTask("b", {8: 60.0})
        prev = solve([t1, t2], topo(8))
        p = resolve([t2], topo(8), prev, interval=10.0)
        assert set(p.assignments) == {"b"}
        assert p.assignments["b"].start == pytest.approx(0.0, abs=1e-6)


class TestWarmStart:
    """VERDICT r1 item 4: seed the interval re-solve from the previous plan
    (reference warmStart, ``milp.py:103-104,151-155,323``)."""

    @staticmethod
    def _rand_tasks(n, seed=0, cap=8):
        rng = np.random.default_rng(seed)
        ts = []
        for i in range(n):
            base = float(rng.uniform(20, 200))
            rts = {
                s: base / (s ** float(rng.uniform(0.6, 0.95)))
                for s in (1, 2, 4, 8)
                if s <= cap
            }
            ts.append(FakeTask(f"t{i}", rts))
        return ts

    def test_warm_schedule_pins_choices(self):
        from saturn_tpu.solver.milp import warm_schedule

        tasks = self._rand_tasks(6)
        prev = solve(tasks, topo(8), time_limit=10.0)
        w = warm_schedule(tasks, topo(8), prev)
        assert w is not None
        for t in tasks:
            assert (
                w.assignments[t.name].apportionment
                == prev.assignments[t.name].apportionment
            )
            assert (
                w.assignments[t.name].block.offset
                == prev.assignments[t.name].block.offset
            )
        # feasible: overlapping blocks separated in time
        items = list(w.assignments.values())
        for i, a in enumerate(items):
            for b in items[i + 1 :]:
                if a.block.overlaps(b.block):
                    assert (
                        a.start + a.runtime <= b.start + 1e-6
                        or b.start + b.runtime <= a.start + 1e-6
                    )

    def test_warm_schedule_none_when_choice_gone(self):
        from saturn_tpu.solver.milp import warm_schedule

        tasks = self._rand_tasks(4)
        prev = solve(tasks, topo(8), time_limit=10.0)
        # a task whose previous assignment no longer exists
        newcomer = FakeTask("new", {4: 50.0})
        assert warm_schedule(tasks + [newcomer], topo(8), prev) is None

    def test_warm_solve_never_worse(self):
        tasks = self._rand_tasks(8, seed=3)
        prev = solve(tasks, topo(8), time_limit=5.0)
        w = solve(tasks, topo(8), time_limit=5.0, warm=prev)
        # warm cut guarantees <= fix-and-optimize of prev; allow numeric slop
        from saturn_tpu.solver.milp import warm_schedule

        bound = warm_schedule(tasks, topo(8), prev).makespan
        assert w.makespan <= bound + 1e-3

    def test_warm_timeout_returns_warm_plan(self):
        """With a starved time limit the warm path must return the
        fix-and-optimize plan, not the greedy fallback."""
        tasks = self._rand_tasks(12, seed=5)
        prev = greedy_plan(tasks, topo(8))
        w = solve(tasks, topo(8), time_limit=1e-4, warm=prev)
        from saturn_tpu.solver.milp import warm_schedule

        bound = warm_schedule(tasks, topo(8), prev).makespan
        assert w.makespan <= bound + 1e-3

    @pytest.mark.slow
    def test_resolve_warm_budget_fast(self):
        """Interval-2 re-solve gets warm_budget_frac of the budget and stays
        same-or-better than the slid previous plan (the VERDICT 'interval-2
        solve time << interval-1' criterion)."""
        import time as _time

        tasks = self._rand_tasks(12, seed=7)
        t0 = _time.perf_counter()
        prev = solve(tasks, topo(8), time_limit=20.0)
        cold_dt = _time.perf_counter() - t0

        t0 = _time.perf_counter()
        p2 = resolve(
            tasks, topo(8), prev, interval=0.0, threshold=0.0,
            time_limit=20.0, warm_budget_frac=0.1,
        )
        warm_dt = _time.perf_counter() - t0
        # budget: 10% of 20s (+ model build); generous 2x slop for CI noise
        assert warm_dt <= max(4.0, cold_dt * 0.5)
        assert p2.makespan <= prev.makespan + 1e-3

    def test_native_warm_seeding(self):
        """Native path: warm seeding must never produce a worse plan than
        the same call without it."""
        from saturn_tpu.solver import native_sched

        if not native_sched.available():
            pytest.skip("native scheduler unavailable")
        tasks = self._rand_tasks(16, seed=11)
        cold = native_sched.solve_native(tasks, topo(8), time_limit=0.3)
        prev = greedy_plan(tasks, topo(8))
        warm = native_sched.solve_native(
            tasks, topo(8), time_limit=0.3, warm=prev
        )
        assert cold is not None and warm is not None
        assert warm.makespan <= max(cold.makespan, prev.makespan) + 1e-6


class TestIncrementalWarmStart:
    """Online-service solver path: arrivals/departures re-solve warm-started
    from the live plan, with priority weights breaking start-time ties."""

    _rand_tasks = staticmethod(TestWarmStart._rand_tasks)

    def test_insert_missing_extends_warm_plan(self):
        from saturn_tpu.solver.milp import warm_schedule

        tasks = self._rand_tasks(5, seed=11)
        prev = solve(tasks, topo(8), time_limit=10.0)
        newcomer = FakeTask("new", {2: 40.0, 4: 25.0})
        w = warm_schedule(
            tasks + [newcomer], topo(8), prev, insert_missing=True
        )
        assert w is not None and "new" in w.assignments
        # incumbents keep their previous (size, block) choices
        for t in tasks:
            assert (
                w.assignments[t.name].apportionment
                == prev.assignments[t.name].apportionment
            )
            assert (
                w.assignments[t.name].block.offset
                == prev.assignments[t.name].block.offset
            )
        # and the extended plan is feasible (overlaps serialized in time)
        items = list(w.assignments.values())
        for i, a in enumerate(items):
            for b in items[i + 1:]:
                if a.block.overlaps(b.block):
                    assert (
                        a.start + a.runtime <= b.start + 1e-6
                        or b.start + b.runtime <= a.start + 1e-6
                    )

    def test_insert_missing_default_off(self):
        # the historical contract: without insert_missing the warm start
        # refuses instances whose task set changed
        from saturn_tpu.solver.milp import warm_schedule

        tasks = self._rand_tasks(4, seed=12)
        prev = solve(tasks, topo(8), time_limit=10.0)
        newcomer = FakeTask("new", {4: 50.0})
        assert warm_schedule(tasks + [newcomer], topo(8), prev) is None

    def test_resolve_with_arrival_not_worse_than_cold(self):
        """Re-solving with one task ADDED, warm-started from the live plan,
        must not degrade makespan vs a cold solve of the same instance."""
        tasks = self._rand_tasks(5, seed=13)
        prev = solve(tasks, topo(8), time_limit=10.0)
        newcomer = FakeTask("new", {2: 60.0, 4: 35.0, 8: 22.0})
        grown = tasks + [newcomer]
        warm = resolve(grown, topo(8), prev, interval=1.0, time_limit=10.0)
        cold = solve(grown, topo(8), time_limit=10.0)
        assert warm.makespan <= cold.makespan + 1e-3
        assert set(warm.assignments) == {t.name for t in grown}

    def test_resolve_with_departure_not_worse_than_cold(self):
        """Re-solving with one task REMOVED must not degrade either."""
        tasks = self._rand_tasks(6, seed=14)
        prev = solve(tasks, topo(8), time_limit=10.0)
        shrunk = tasks[:-1]
        warm = resolve(shrunk, topo(8), prev, interval=1.0, time_limit=10.0)
        cold = solve(shrunk, topo(8), time_limit=10.0)
        assert warm.makespan <= cold.makespan + 1e-3
        assert set(warm.assignments) == {t.name for t in shrunk}

    def test_weights_order_makespan_equal_schedules(self):
        """Three identical full-mesh tasks serialize; the weighted objective
        must start the high-weight task first without hurting makespan."""
        tasks = [FakeTask(n, {8: 50.0}) for n in ("a", "b", "c")]
        base = solve(tasks, topo(8), time_limit=10.0)
        w = solve(tasks, topo(8), time_limit=10.0,
                  weights={"c": 1.0, "a": 0.0, "b": 0.0})
        assert w.makespan == pytest.approx(base.makespan, rel=0.01)
        assert w.assignments["c"].start == pytest.approx(0.0, abs=1e-6)
        assert all(
            w.assignments[n].start >= 50.0 - 1e-6 for n in ("a", "b")
        )

    def test_greedy_plan_respects_weights(self):
        tasks = [FakeTask(n, {8: 30.0}) for n in ("lo", "mid", "hi")]
        p = greedy_plan(tasks, topo(8),
                        weights={"hi": 4.0, "mid": 2.0, "lo": 0.0})
        assert p.assignments["hi"].start == pytest.approx(0.0, abs=1e-9)
        assert p.assignments["mid"].start < p.assignments["lo"].start
