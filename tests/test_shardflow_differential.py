"""Differential oracle for the shardflow communication ledger: the static
analyzer's per-collective byte totals must agree with what XLA actually
compiles for the same step function, for every built-in SPMD technique.

Each of the six strategies (dp/fsdp/tp/ep/ring/ulysses) is traced twice:

* **statically** — ``trace_step`` -> abstract jaxpr -> the shardflow
  interpreter's :class:`CommLedger` (no devices, no compile);
* **for real** — the same step jitted with the traced input shardings,
  compiled by XLA for 4 virtual CPU devices, and the collectives
  regex-extracted from the optimized HLO text.

The comparable quantity is the **per-technique total byte volume**, not
raw op counts, because XLA legally rewrites between equivalent forms:

* an all-gather of a sharded operand may compile to an all-to-all +
  collective-permute chain (fsdp's parameter gathers do);
* adjacent all-reduces are combined or split by the combiner pass, so
  counts drift while bytes are conserved;
* the analyzer models reduce-scatter-as-all-reduce for optimizer states
  it cannot prove are resharded (pessimistic, never under-counts).

Calibrated on this image: dp 0.89, tp 1.04, ep 0.84, ring and ulysses
byte-exact on their signature collectives, fsdp 0.62 (the gather
decomposition above). The gate is a total-bytes ratio in [0.45, 2.2] —
wide enough for rewrite slack, tight enough that a broken propagation
rule (which typically loses or invents whole tensors, i.e. >=4x) fails.
Signature collectives are held tighter: ring must show ppermute and
ulysses all-to-all on both sides, bytes within [0.5, 2.0].

The HLO shape-bytes parser is itself property-tested against a naive
reference on generated shape strings — with hypothesis when the image
carries it, else a seeded ``random.Random`` sweep (the suite must not
depend on an uninstalled package).
"""

import random
import re

import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec

from saturn_tpu.analysis.shardflow.interp import interpret
from saturn_tpu.core.mesh import make_submesh

pytestmark = pytest.mark.analysis

SIZE = 4

#: total static bytes / total HLO bytes must land here (see module doc)
TOTAL_RATIO = (0.45, 2.2)
#: signature-collective bytes (ring ppermute, ulysses all-to-all)
SIGNATURE_RATIO = (0.5, 2.0)

TECHNIQUES = ["dp", "fsdp", "tp", "ep", "ring", "ulysses"]
SIGNATURES = {"ring": "ppermute", "ulysses": "all_to_all"}

#: Bands for the overlapped (collective-matmul / ZeRO-3 prefetch) grid
#: points, wider on top than TOTAL_RATIO for two *legal* deflations of the
#: HLO side: (1) the static ledger folds scan trip counts (xL gathers in
#: the layer loop) while the optimized-HLO text lists each while-body
#: instruction once; (2) the collective-permute combiner merges per-leaf
#: hop chains. Both grow with the gather ring size — calibrated on this
#: image: fsdp (S=4) total 3.4 / ppermute 4.5, tp (S=2) total 1.3 /
#: ppermute 1.5. The floor still catches a propagation rule that loses
#: whole tensors; the ceiling catches invented ones beyond the fold.
OVERLAP_TOTAL_RATIO = (0.45, 4.5)
OVERLAP_PPERMUTE_RATIO = (0.5, 6.0)

# --------------------------------------------------------------------------
# HLO collective extraction
# --------------------------------------------------------------------------
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1,
    "pred": 1,
}
_CANON = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "ppermute",
}
_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.-]+\s*=\s*(\([^=]*?\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\(",
    re.M,
)
_SHAPE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")


def shape_bytes(shape_str):
    """Total payload bytes of one HLO shape string (tuples included)."""
    total = 0
    for m in _SHAPE.finditer(shape_str):
        dtype, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def hlo_collectives(hlo_text):
    """Aggregate {op: {count, bytes}} over an optimized HLO module."""
    out = {}
    for m in _INSTR.finditer(hlo_text):
        op = _CANON[m.group(2)]
        row = out.setdefault(op, {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += shape_bytes(m.group(1))
    return out


# --------------------------------------------------------------------------
# tasks and the trace/compile harness
# --------------------------------------------------------------------------
@pytest.fixture()
def moe_task(tmp_path):
    """The MoE sibling of ``tiny_task`` — required by the 'ep' technique."""
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    return Task(
        get_model=lambda **kw: build_gpt2("moe-test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256,
            n_tokens=64 * 8 * 2),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=4),
        save_dir=str(tmp_path / "moe-ckpts"),
    )


def _technique(name):
    from saturn_tpu import library as lib

    if not lib.registered_names():
        lib.register_default_library()
    cls = lib.retrieve(name)
    return cls() if isinstance(cls, type) else cls


def trace_and_compile(name, task, devices):
    """One technique, both ways: (static CommLedger, HLO collective map)."""
    tech = _technique(name)
    config = tech.candidate_configs(task, SIZE)[0]
    traced = tech.trace_step(task, devices, config)
    ledger = interpret(traced)

    axis_names, axis_sizes = tech.mesh_spec(SIZE, task, config)
    mesh = make_submesh(devices, axis_names, axis_sizes)
    spec = task.get_model(**tech._model_overrides(config)) \
        if hasattr(tech, "_model_overrides") else task.get_model()
    ds = task.get_dataset()
    _, train_step = tech.make_step_fns(spec, task, config, mesh, ds)

    state_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s if s is not None else PartitionSpec()),
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    batch_sh = NamedSharding(mesh, traced["batch_spec"])
    compiled = (
        jax.jit(train_step, in_shardings=(state_sh, batch_sh))
        .lower(traced["state_shapes"], traced["batch_sds"])
        .compile()
    )
    return ledger, hlo_collectives(compiled.as_text())


# --------------------------------------------------------------------------
# the differential gate
# --------------------------------------------------------------------------
@pytest.mark.parametrize("name", TECHNIQUES)
def test_static_ledger_matches_compiled_collectives(
        name, tiny_task, moe_task, devices8):
    task = moe_task if name == "ep" else tiny_task
    ledger, hlo = trace_and_compile(name, task, devices8[:SIZE])

    assert ledger.records, f"{name}: static ledger is empty"
    assert hlo, f"{name}: compiled program has no collectives"

    static_total = ledger.total_bytes()
    hlo_total = sum(row["bytes"] for row in hlo.values())
    ratio = static_total / hlo_total
    lo, hi = TOTAL_RATIO
    assert lo <= ratio <= hi, (
        f"{name}: static {static_total}B vs compiled {hlo_total}B "
        f"(ratio {ratio:.2f} outside [{lo}, {hi}]) — "
        f"static={ledger.by_op()} hlo={hlo}"
    )

    sig = SIGNATURES.get(name)
    if sig is not None:
        by = ledger.by_op()
        assert sig in by, f"{name}: static ledger missing its {sig}"
        assert sig in hlo, f"{name}: compiled HLO missing its {sig}"
        sig_ratio = by[sig]["bytes"] / hlo[sig]["bytes"]
        slo, shi = SIGNATURE_RATIO
        assert slo <= sig_ratio <= shi, (
            f"{name}: {sig} bytes static {by[sig]['bytes']} vs compiled "
            f"{hlo[sig]['bytes']} (ratio {sig_ratio:.2f})"
        )


@pytest.mark.parametrize("name", ["fsdp", "tp"])
def test_overlapped_lowering_ledger_matches_compiled(
        name, tiny_task, devices8):
    """The collective-matmul / ZeRO-3 prefetch grid points trace to an
    explicit shard_map program (ring gathers as ppermute chains instead of
    GSPMD's inferred all-gathers). The static ledger must still track the
    compiled bytes, and the signature op — ppermute — must appear on both
    sides: the overlapped lowering gets the same differential gate as the
    serial techniques, not a free pass."""
    tech = _technique(name)
    configs = [c for c in tech.candidate_configs(tiny_task, SIZE)
               if c.get("overlap")]
    assert configs, f"{name}: no overlap grid point for the tiny task"
    config = configs[0]

    devices = devices8[:SIZE]
    traced = tech.trace_step(tiny_task, devices, config)
    ledger = interpret(traced)

    axis_names, axis_sizes = tech.mesh_spec(SIZE, tiny_task, config)
    mesh = make_submesh(devices, axis_names, axis_sizes)
    spec = tiny_task.get_model(**tech._model_overrides(config))
    ds = tiny_task.get_dataset()
    _, train_step = tech.make_step_fns(spec, tiny_task, config, mesh, ds)
    state_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s if s is not None else PartitionSpec()),
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    batch_sh = NamedSharding(mesh, traced["batch_spec"])
    compiled = (
        jax.jit(train_step, in_shardings=(state_sh, batch_sh))
        .lower(traced["state_shapes"], traced["batch_sds"])
        .compile()
    )
    hlo = hlo_collectives(compiled.as_text())

    assert ledger.records, f"{name}+overlap: static ledger is empty"
    assert hlo, f"{name}+overlap: compiled program has no collectives"
    static_total = ledger.total_bytes()
    hlo_total = sum(row["bytes"] for row in hlo.values())
    ratio = static_total / hlo_total
    lo, hi = OVERLAP_TOTAL_RATIO
    assert lo <= ratio <= hi, (
        f"{name}+overlap: static {static_total}B vs compiled {hlo_total}B "
        f"(ratio {ratio:.2f} outside [{lo}, {hi}]) — "
        f"static={ledger.by_op()} hlo={hlo}"
    )
    by = ledger.by_op()
    assert "ppermute" in by, (
        f"{name}+overlap: static ledger lost the ring-gather hops: {by}")
    assert "ppermute" in hlo, (
        f"{name}+overlap: compiled HLO lost the ring-gather hops: {hlo}")
    sig_ratio = by["ppermute"]["bytes"] / hlo["ppermute"]["bytes"]
    slo, shi = OVERLAP_PPERMUTE_RATIO
    assert slo <= sig_ratio <= shi, (
        f"{name}+overlap: ppermute bytes static {by['ppermute']['bytes']} "
        f"vs compiled {hlo['ppermute']['bytes']} (ratio {sig_ratio:.2f})"
    )


def test_dense_techniques_agree_on_flops(tiny_task, devices8):
    """dp, fsdp and tp shard the same model; the analyzer must report the
    same global flop count for all three regardless of trace style
    (GSPMD trace vs per-shard shard_map bodies)."""
    flops = {}
    for name in ("dp", "fsdp", "tp"):
        tech = _technique(name)
        config = tech.candidate_configs(tiny_task, SIZE)[0]
        traced = tech.trace_step(tiny_task, devices8[:SIZE], config)
        flops[name] = interpret(traced).flops
    base = flops["dp"]
    assert base > 0
    for name, f in flops.items():
        assert f == pytest.approx(base, rel=0.25), flops


# --------------------------------------------------------------------------
# property test: the HLO shape parser vs a naive reference
# --------------------------------------------------------------------------
def _reference_bytes(shapes):
    """Independent oracle: (dtype, dims) pairs -> total bytes."""
    total = 0
    for dtype, dims in shapes:
        n = 1
        for d in dims:
            n *= d
        total += n * _DTYPE_BYTES[dtype]
    return total


def _render(shapes, rng):
    """Render (dtype, dims) pairs the way optimized HLO prints them."""
    parts = []
    for dtype, dims in shapes:
        layout = ""
        if dims and rng.random() < 0.5:
            order = list(range(len(dims)))[::-1]
            layout = "{" + ",".join(str(i) for i in order) + "}"
        parts.append(f"{dtype}[{','.join(str(d) for d in dims)}]{layout}")
    if len(parts) == 1 and rng.random() < 0.7:
        return parts[0]
    return "(" + ", ".join(parts) + ")"


def _random_shapes(rng):
    n = rng.randint(1, 4)
    return [
        (rng.choice(sorted(_DTYPE_BYTES)),
         [rng.randint(1, 64) for _ in range(rng.randint(0, 3))])
        for _ in range(n)
    ]


def _check_one(rng):
    shapes = _random_shapes(rng)
    rendered = _render(shapes, rng)
    line = f"  %x.{rng.randint(1, 99)} = {rendered} all-reduce(%y.1)"
    parsed = hlo_collectives(line)
    assert parsed == {
        "all_reduce": {"count": 1, "bytes": _reference_bytes(shapes)}
    }, (rendered, shapes)


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @settings(max_examples=300, deadline=None)
    @given(st.integers(min_value=0, max_value=2 ** 32))
    def test_shape_parser_matches_reference(seed):
        _check_one(random.Random(seed))

except ImportError:

    def test_shape_parser_matches_reference():
        rng = random.Random(20260805)
        for _ in range(1000):
            _check_one(rng)
