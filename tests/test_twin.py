"""saturn-twin (round 22): the discrete-event fleet simulator that runs the
REAL control plane — gateway, admission, anytime solver, pressure shed,
elastic replan — against virtual slices on a virtual clock.

The tentpole claims under test:

- **Determinism**: same seed + config (+ trace) ⇒ bit-identical
  ``events.jsonl`` and final verdict ledger across repeated runs — including
  a seeded TopologyChange-storm campaign (preemptions, crashes, stragglers).
- **Replayability**: twin journals are real write-ahead journals; a
  campaign's own journal replays through the twin and lands within the
  documented fidelity band (``trace.DEFAULT_BAND``).
- **Reconciled replay**: ``journal.replay_reconciled`` merges overlapping
  writer incarnations in stable ``(seq, incarnation)`` order where strict
  replay would silently drop the later incarnation.
- **Operator surface**: ``python -m saturn_tpu.analysis twin`` reports
  makespan / tier shares / admission mix / shed counts / fidelity deltas,
  and can run synth, storm, replay and capacity-what-if campaigns itself.

Solver budgets in these tests are deliberately generous (30 real seconds):
the anytime ladder races ``time.perf_counter`` — which the twin leaves
unpatched on purpose — so bit-identity is only guaranteed when every
attempted tier finishes inside its budget on any host.
"""

import importlib.util
import json
import os
import sys
import time
import timeit
import zlib

import pytest

from saturn_tpu.durability import journal as jmod
from saturn_tpu.twin.arrivals import BURST_EVERY, BURST_LEN, arrival_stream
from saturn_tpu.twin.clock import EventQueue, VirtualClock
from saturn_tpu.twin.runner import CampaignConfig, run_campaign, run_what_if
from saturn_tpu.twin.trace import DEFAULT_BAND, fidelity_compare, load_trace

pytestmark = pytest.mark.twin

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


@pytest.fixture(autouse=True)
def _small_partitions(monkeypatch):
    # Pin the tier-1 partition width (a documented operator knob) so every
    # MILP instance the campaigns generate proves optimality in milliseconds.
    # A MILP that instead hits its HiGHS time_limit returns a wall-clock-
    # dependent incumbent — on a loaded host that breaks the bit-identity
    # these tests assert (probed: the seed-3 storm's post-grow 24-task solve
    # grinds 48s uncapped at the default width, 1s at width 4).
    monkeypatch.setenv("SATURN_TPU_PARTITION_MAX", "4")

#: Generous real-clock solver budget: every tier the ladder attempts must
#: finish, so tier adoption (and with it the event log) cannot race.
SAFE_SOLVE_S = 30.0

#: The seeded storm campaign (probed: topology changes, transient crashes,
#: preemption requeues AND one retry-budget exhaustion all fire).
STORM_CFG = dict(
    n_jobs=24, n_slices=2, interval_s=12.0, total_batches=6,
    solve_deadline_s=SAFE_SOLVE_S, metrics=False, seed=3, storm=True,
    storm_p_preempt=0.6, storm_p_crash=0.5, storm_p_straggler=0.3,
    outage_intervals=1, max_intervals=80,
)


def _campaign_bytes(out_dir):
    """The determinism contract: the event log and the verdict ledger."""
    out = {}
    for fn in ("events.jsonl", "ledger.json"):
        with open(os.path.join(out_dir, fn), "rb") as fh:
            out[fn] = fh.read()
    return out


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------
# virtual clock + event queue
# --------------------------------------------------------------------------
class TestVirtualClock:
    def test_patch_swaps_and_restores_time_sources(self):
        real_time = time.time
        real_mono = time.monotonic
        with VirtualClock(start=100.0).patch() as clk:
            assert time.time() == 100.0
            assert time.monotonic() == 100.0
            assert timeit.default_timer() == 100.0
            time.sleep(5.5)  # advances instead of blocking
            assert time.time() == 105.5
            assert clk.now() == 105.5
        assert time.time is real_time
        assert time.monotonic is real_mono
        assert time.time() > 1e9  # actually back on the epoch clock

    def test_perf_counter_stays_real_under_patch(self):
        # The solver's deadline race must burn honest CPU time.
        with VirtualClock().patch():
            a = time.perf_counter()
            for _ in range(10_000):
                pass
            assert time.perf_counter() >= a
            assert time.perf_counter() != time.time()

    def test_advance_contract(self):
        clk = VirtualClock(start=10.0)
        with pytest.raises(ValueError):
            clk.advance(-1.0)
        assert clk.advance_to(5.0) == 10.0   # never goes backwards
        assert clk.advance_to(12.0) == 12.0
        clk.sleep(-3.0)                       # clamps like time.sleep
        assert clk.now() == 12.0

    def test_restores_on_exception(self):
        real_time = time.time
        with pytest.raises(RuntimeError):
            with VirtualClock().patch():
                raise RuntimeError("boom")
        assert time.time is real_time

    def test_event_queue_breaks_ties_by_insertion_order(self):
        q = EventQueue()
        q.push(2.0, "b")
        q.push(1.0, "tie-first")
        q.push(1.0, "tie-second")
        assert q.peek_time() == 1.0
        assert len(q) == 3 and not q.empty
        due = q.pop_due(1.0)
        assert [k for _, k, _ in due] == ["tie-first", "tie-second"]
        assert q.pop_due(5.0) == [(2.0, "b", None)]
        assert q.empty


# --------------------------------------------------------------------------
# arrivals (satellite: extracted generator, shared with the gateway bench)
# --------------------------------------------------------------------------
class TestArrivals:
    def test_deterministic_across_calls(self):
        a = arrival_stream(200, base_rate_hz=12.0, burst_rate_hz=80.0, seed=7)
        b = arrival_stream(200, base_rate_hz=12.0, burst_rate_hz=80.0, seed=7)
        assert a == b
        assert a != arrival_stream(
            200, base_rate_hz=12.0, burst_rate_hz=80.0, seed=8
        )

    def test_diurnal_burst_shape(self):
        trace = arrival_stream(BURST_EVERY + 5, base_rate_hz=2.0,
                               burst_rate_hz=50.0, seed=1)
        assert all(t.in_burst for t in trace[:BURST_LEN])
        assert not any(t.in_burst for t in trace[BURST_LEN:BURST_EVERY])
        assert all(t.in_burst for t in trace[BURST_EVERY:])
        offsets = [t.at_s for t in trace]
        assert offsets == sorted(offsets)
        assert all(t.priority in (0.0, 1.0, 2.0) for t in trace)

    def test_rejects_nonsense(self):
        with pytest.raises(ValueError):
            arrival_stream(-1, base_rate_hz=1.0, burst_rate_hz=1.0)
        with pytest.raises(ValueError):
            arrival_stream(1, base_rate_hz=0.0, burst_rate_hz=1.0)
        with pytest.raises(ValueError):
            arrival_stream(1, base_rate_hz=1.0, burst_rate_hz=1.0,
                           burst_every=0)

    def test_gateway_bench_imports_the_same_generator(self):
        # The bench must consume the twin's generator, not a fork of it.
        sys.path.insert(0, os.path.join(REPO, "benchmarks"))
        try:
            import online_arrivals
        finally:
            sys.path.pop(0)
        assert online_arrivals.arrival_stream is arrival_stream
        assert online_arrivals.BURST_EVERY == BURST_EVERY
        assert online_arrivals.BURST_LEN == BURST_LEN


# --------------------------------------------------------------------------
# reconciled journal replay (satellite: stable (seq, incarnation) merge)
# --------------------------------------------------------------------------
def _write_segment(root, index, records):
    """Hand-build a CRC-valid journal segment: records = [(seq, data)]."""
    lines = []
    for seq, data in records:
        body = {"seq": seq, "ts": float(seq), "kind": "job_state",
                "data": data}
        crc = format(
            zlib.crc32(json.dumps(
                body, sort_keys=True, separators=(",", ":"), default=str
            ).encode("utf-8")), "08x")
        body["crc"] = crc
        lines.append(json.dumps(body, sort_keys=True,
                                separators=(",", ":"), default=str))
    path = os.path.join(root, f"wal-{index:06d}.jsonl")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")


class TestReplayReconciled:
    def test_overlapping_incarnations_merge_latest_wins(self, tmp_path):
        root = str(tmp_path)
        # Incarnation 0: seqs 1..6 over two contiguous segments.
        _write_segment(root, 0, [(s, {"inc": 0, "seq": s}) for s in (1, 2, 3)])
        _write_segment(root, 1, [(s, {"inc": 0, "seq": s}) for s in (4, 5, 6)])
        # Incarnation 1 restarted from an OLDER durable cut: its segment
        # re-uses seqs 4..6, then extends the history to 8.
        _write_segment(root, 2,
                       [(s, {"inc": 1, "seq": s}) for s in (4, 5, 6, 7, 8)])

        # Strict single-history replay stops at the discontinuity: the
        # entire later incarnation (including the 7..8 tail) is dropped.
        strict = jmod.replay(root)
        assert [r["seq"] for r in strict] == [1, 2, 3, 4, 5, 6]
        assert all(r["data"]["inc"] == 0 for r in strict)

        # Reconciled replay keeps the union, later incarnation winning
        # where the sequence ranges overlap.
        merged = jmod.replay_reconciled(root)
        assert [r["seq"] for r in merged] == [1, 2, 3, 4, 5, 6, 7, 8]
        by_seq = {r["seq"]: r["data"]["inc"] for r in merged}
        assert by_seq == {1: 0, 2: 0, 3: 0, 4: 1, 5: 1, 6: 1, 7: 1, 8: 1}

    def test_single_incarnation_matches_strict_replay(self, tmp_path):
        root = str(tmp_path)
        _write_segment(root, 0, [(s, {"inc": 0}) for s in (1, 2)])
        _write_segment(root, 1, [(s, {"inc": 0}) for s in (3, 4)])
        assert jmod.replay_reconciled(root) == jmod.replay(root)

    def test_corrupt_record_is_skipped_not_fatal(self, tmp_path):
        root = str(tmp_path)
        _write_segment(root, 0, [(s, {"inc": 0}) for s in (1, 2, 3)])
        with open(os.path.join(root, "wal-000000.jsonl"), "a") as fh:
            fh.write("{torn garbage\n")
        merged = jmod.replay_reconciled(root)
        assert [r["seq"] for r in merged] == [1, 2, 3]


# --------------------------------------------------------------------------
# tentpole: campaign determinism (bit-identical event log + ledger)
# --------------------------------------------------------------------------
class TestCampaignDeterminism:
    def _run_n(self, cfg, tmp_path, n=3):
        outs = []
        for i in range(n):
            d = str(tmp_path / f"run{i}")
            summary = run_campaign(cfg, d)
            outs.append((summary, _campaign_bytes(d)))
        return outs

    def test_synth_campaign_bit_identical_across_3_runs(self, tmp_path):
        cfg = CampaignConfig(n_jobs=30, n_slices=2, interval_s=60.0,
                             solve_deadline_s=SAFE_SOLVE_S, metrics=False,
                             seed=11)
        outs = self._run_n(cfg, tmp_path)
        blobs = [b for _, b in outs]
        assert blobs[0]["events.jsonl"]  # non-trivial log
        assert blobs[0] == blobs[1] == blobs[2]
        summary = outs[0][0]
        assert summary["status"] == "ok"
        assert summary["completed"] == 30
        assert summary["deadline_misses"] == 0
        # The ledger is the deterministic side; wall_s lives only in the
        # summary and is the one intentionally non-deterministic field.
        ledger = json.loads(blobs[0]["ledger.json"])
        assert "wall_s" not in ledger

    def test_storm_campaign_bit_identical_and_chaotic(self, tmp_path):
        cfg = CampaignConfig(**STORM_CFG)
        outs = self._run_n(cfg, tmp_path)
        blobs = [b for _, b in outs]
        assert blobs[0] == blobs[1] == blobs[2]
        summary = outs[0][0]
        assert summary["status"] == "ok"
        assert summary["deadline_misses"] == 0
        # The storm actually stormed — and the control plane rode it out.
        assert summary["topology_changes"] >= 2
        assert summary["preemption_requeues"] > 0
        assert summary["crashes"] > 0
        assert summary["completed"] + summary["failed"] == cfg.n_jobs
        kinds = {json.loads(line)["kind"]
                 for line in blobs[0]["events.jsonl"].decode().splitlines()}
        assert {"topology_change", "task_preempted", "solve",
                "job_completed"} <= kinds

    def test_different_seed_diverges(self, tmp_path):
        base = dict(n_jobs=16, n_slices=2, interval_s=60.0,
                    solve_deadline_s=SAFE_SOLVE_S, metrics=False)
        a = str(tmp_path / "a")
        b = str(tmp_path / "b")
        run_campaign(CampaignConfig(seed=1, **base), a)
        run_campaign(CampaignConfig(seed=2, **base), b)
        assert (_campaign_bytes(a)["events.jsonl"]
                != _campaign_bytes(b)["events.jsonl"])

    def test_dedup_retry_storm_collapses_idempotently(self, tmp_path):
        cfg = CampaignConfig(n_jobs=25, n_slices=2, interval_s=60.0,
                             solve_deadline_s=SAFE_SOLVE_S, metrics=False,
                             seed=5, dedup_every=5)
        summary = run_campaign(cfg, str(tmp_path / "dedup"))
        # Every 5th arrival resubmits its predecessor's idempotency key and
        # must collapse through the real gateway dedup table.
        assert summary["duplicates"] == (cfg.n_jobs - 1) // cfg.dedup_every
        assert summary["submitted"] == cfg.n_jobs - summary["duplicates"]
        assert summary["completed"] == summary["submitted"]


# --------------------------------------------------------------------------
# fidelity: twin journals are replayable traces; replays land in band
# --------------------------------------------------------------------------
class TestReplayFidelity:
    def test_campaign_journal_replays_within_band(self, tmp_path):
        cfg = CampaignConfig(n_jobs=20, n_slices=2, interval_s=30.0,
                             solve_deadline_s=SAFE_SOLVE_S, metrics=False,
                             seed=9)
        a_dir = str(tmp_path / "original")
        a = run_campaign(cfg, a_dir)
        journal_dir = os.path.join(a_dir, "journal")

        trace = load_trace(journal_dir)
        assert len(trace.jobs) == a["submitted"]
        assert set(trace.admission_mix) <= {"admit", "defer", "reject"}
        offsets = [j.at_s for j in trace.jobs]
        assert offsets == sorted(offsets) and offsets[0] == 0.0

        b_cfg = CampaignConfig(trace_dir=journal_dir, n_slices=2,
                               interval_s=30.0,
                               solve_deadline_s=SAFE_SOLVE_S,
                               metrics=False, seed=9)
        b = run_campaign(b_cfg, str(tmp_path / "replay"))
        assert b["status"] == "ok"
        assert b["completed"] == a["completed"]
        cmp = fidelity_compare(
            {"tier_shares": b["tier_shares"],
             "verdict_shares": b["verdict_shares"],
             "makespan_s": b["makespan_s"]},
            {"tier_shares": a["tier_shares"],
             "verdict_shares": a["verdict_shares"],
             "makespan_s": a["makespan_s"]},
        )
        assert cmp["within_band"], cmp

    def test_fidelity_compare_band_edges(self):
        flat = {"tier_shares": {"1": 1.0}, "verdict_shares": {"admit": 1.0},
                "makespan_s": 10.0}
        assert fidelity_compare(flat, dict(flat))["within_band"]
        # A tier distribution further than the band allows.
        drifted = dict(flat, tier_shares={"2": 1.0})
        out = fidelity_compare(drifted, flat)
        assert not out["within_band"]
        assert out["tier_share_deltas"] == {"1": 1.0, "2": 1.0}
        # Makespan ratio outside [0.3, 3.0].
        slow = dict(flat, makespan_s=10.0 * DEFAULT_BAND["makespan_ratio"][1]
                    * 1.5)
        assert not fidelity_compare(slow, flat)["within_band"]
        # Empty-on-both-sides compares equal.
        empty = {"tier_shares": {}, "verdict_shares": {}, "makespan_s": 0.0}
        assert fidelity_compare(empty, dict(empty))["within_band"]


# --------------------------------------------------------------------------
# capacity what-if: base vs +1 slice vs relaxed deadlines, same arrivals
# --------------------------------------------------------------------------
class TestWhatIf:
    def test_relaxing_deadlines_attributably_reduces_evictions(self, tmp_path):
        base = CampaignConfig(n_jobs=24, n_slices=2, interval_s=30.0,
                              deadline_s=35.0,
                              solve_deadline_s=SAFE_SOLVE_S,
                              metrics=False, seed=7)
        verdict = run_what_if(base, str(tmp_path))
        cmp = verdict["comparison"]
        assert set(cmp) == {"base", "add-slice", "relax-deadlines"}
        # Tight deadlines make the pressure projection shed under load;
        # doubling every deadline (same seed, same arrivals) must strictly
        # help, and the delta is attributable to the knob alone.
        assert cmp["base"]["evicted"] > 0
        assert (cmp["relax-deadlines"]["evicted"] < cmp["base"]["evicted"])
        assert (cmp["relax-deadlines"]["completed"]
                > cmp["base"]["completed"])
        assert os.path.exists(os.path.join(str(tmp_path), "whatif.json"))
        with open(os.path.join(str(tmp_path), "whatif.json")) as fh:
            assert json.load(fh)["comparison"] == cmp


# --------------------------------------------------------------------------
# operator surface: python -m saturn_tpu.analysis twin
# --------------------------------------------------------------------------
class TestTwinCLI:
    @pytest.fixture()
    def campaign_dir(self, tmp_path):
        d = str(tmp_path / "campaign")
        run_campaign(
            CampaignConfig(n_jobs=15, n_slices=2, interval_s=30.0,
                           solve_deadline_s=SAFE_SOLVE_S, metrics=False,
                           seed=13),
            d,
        )
        return d

    def test_inspect_human_and_json(self, campaign_dir, capsys):
        from saturn_tpu.analysis.cli import main

        assert main(["twin", campaign_dir]) == 0
        out = capsys.readouterr().out
        assert "twin campaign ok" in out
        assert "admission:" in out and "solver:" in out

        assert main(["--json", "twin", campaign_dir]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["status"] == "ok"
        assert payload["completed"] == 15
        assert payload["deadline_misses"] == 0
        assert payload["tier_counts"]

    def test_fidelity_deltas_against_own_journal(self, campaign_dir, capsys):
        from saturn_tpu.analysis.cli import main

        rc = main(["--json", "twin", campaign_dir,
                   "--trace", os.path.join(campaign_dir, "journal")])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        fid = payload["fidelity"]
        assert fid["within_band"] is True
        assert all(v <= DEFAULT_BAND["verdict_share_delta"]
                   for v in fid["verdict_share_deltas"].values())

    def test_run_synth_through_cli(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        d = str(tmp_path / "via-cli")
        rc = main(["--json", "twin", d, "--run", "synth",
                   "--jobs", "12", "--slices", "2", "--interval", "30",
                   "--solve-deadline", str(SAFE_SOLVE_S)])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["submitted"] == 12
        for fn in ("events.jsonl", "ledger.json", "summary.json"):
            assert os.path.exists(os.path.join(d, fn))

    def test_run_storm_through_cli_is_deterministic(self, tmp_path, capsys):
        # The acceptance bar verbatim: a seeded preemption-storm campaign
        # run through the twin CLI produces deterministic journaled
        # verdicts — twice through the front door, identical bytes out.
        from saturn_tpu.analysis.cli import main

        dirs = [str(tmp_path / "s1"), str(tmp_path / "s2")]
        payloads = []
        for d in dirs:
            rc = main(["--json", "twin", d, "--run", "storm",
                       "--jobs", "10", "--slices", "2", "--interval", "30",
                       "--seed", "3",
                       "--solve-deadline", str(SAFE_SOLVE_S)])
            assert rc == 0
            payloads.append(json.loads(capsys.readouterr().out))
        assert payloads[0] == payloads[1]
        assert _campaign_bytes(dirs[0]) == _campaign_bytes(dirs[1])
        assert os.path.isdir(os.path.join(dirs[0], "journal"))

    def test_run_whatif_through_cli(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        d = str(tmp_path / "whatif-cli")
        rc = main(["--json", "twin", d, "--run", "whatif",
                   "--jobs", "12", "--slices", "2", "--interval", "30",
                   "--solve-deadline", str(SAFE_SOLVE_S)])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["whatif"]) == {"base", "add-slice",
                                          "relax-deadlines"}
        # Re-inspecting the directory finds whatif.json.
        assert main(["--json", "twin", d]) == 0
        again = json.loads(capsys.readouterr().out)
        assert again["whatif"] == payload["whatif"]

    def test_usage_errors(self, tmp_path, capsys):
        from saturn_tpu.analysis.cli import main

        assert main(["twin", str(tmp_path / "nope")]) == 2
        assert main(["twin", str(tmp_path / "r"), "--run", "replay"]) == 2
        err = capsys.readouterr().err
        assert "requires --trace" in err


# --------------------------------------------------------------------------
# bench guard: the twin_scale row schema + acceptance bars
# --------------------------------------------------------------------------
class TestTwinRowGuard:
    GOOD = {
        "metric": "twin_scale", "mode": "full", "n_jobs": 100_000,
        "n_slices": 32, "chips": 256, "submitted": 100_000,
        "scheduled": 100_000, "completed": 100_000, "failed": 0,
        "evicted": 0, "shed": 0, "solves": 32, "deadline_misses": 0,
        "tier_counts": {"1": 1, "2": 31}, "makespan_sim_s": 19200.0,
        "wall_s": 131.1, "seed": 7,
        "fidelity": {"within_band": True}, "status": "ok",
    }

    def _guard(self):
        return _load("bench_guard_twin",
                     os.path.join(REPO, "benchmarks", "bench_guard.py"))

    def test_good_row_passes(self):
        assert self._guard().validate_twin_row(dict(self.GOOD)) == []

    def test_deadline_miss_fails(self):
        row = dict(self.GOOD, deadline_misses=1)
        assert any("deadline_misses" in p
                   for p in self._guard().validate_twin_row(row))

    def test_full_mode_scale_floor(self):
        g = self._guard()
        assert any("n_jobs" in p for p in g.validate_twin_row(
            dict(self.GOOD, n_jobs=50_000, submitted=50_000,
                 scheduled=50_000, completed=50_000)))
        assert any("n_slices" in p for p in g.validate_twin_row(
            dict(self.GOOD, n_slices=16)))
        # Quick mode is exempt from the floor.
        assert g.validate_twin_row(
            dict(self.GOOD, mode="quick", n_jobs=2_000, submitted=2_000,
                 scheduled=2_000, completed=2_000)) == []

    def test_conservation_and_fidelity_bars(self):
        g = self._guard()
        assert any("limbo" in p for p in g.validate_twin_row(
            dict(self.GOOD, completed=90_000)))
        assert any("within_band" in p for p in g.validate_twin_row(
            dict(self.GOOD, fidelity={"within_band": False})))
        # An empty fidelity dict (phase skipped) is allowed.
        assert g.validate_twin_row(dict(self.GOOD, fidelity={})) == []

    def test_missing_keys_and_wrong_types(self):
        g = self._guard()
        row = dict(self.GOOD)
        row.pop("tier_counts")
        assert any("tier_counts" in p for p in g.validate_twin_row(row))
        assert g.validate_twin_row([1, 2]) != []
        assert any("bool" in p for p in g.validate_twin_row(
            dict(self.GOOD, deadline_misses=False)))


# --------------------------------------------------------------------------
# the real-service fidelity regression (sockets + threads: slow tier)
# --------------------------------------------------------------------------
@pytest.mark.slow
class TestRealServiceFidelity:
    def test_gateway_bench_journal_replays_within_band(self, tmp_path):
        """The full calibrated-instrument check: a real SaturnService run
        (sockets, threads, real engine stub) journals its arrivals; the twin
        replays that journal; tier shares / verdict mix / makespan agree
        within ``DEFAULT_BAND``. This is exactly what
        ``benchmarks/twin_scale.py``'s fidelity phase gates in CI."""
        sys.path.insert(0, os.path.join(REPO, "benchmarks"))
        try:
            import twin_scale

            row = twin_scale.run_fidelity_phase(str(tmp_path))
        finally:
            sys.path.pop(0)
        assert row["metric"] == "twin_fidelity"
        assert row["within_band"], row
        assert row["deadline_misses"] == 0
