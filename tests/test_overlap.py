"""Comm/compute overlap: the knob must change schedules, never values.

Every overlapped lowering in the repo is gated behind a config knob and
claims a numerical contract against its serial twin:

* ring attention ``overlap`` and the staged pipeline ``overlap`` —
  **bit-identical** (same accumulate ops in the same order; only the hop's
  program position moves);
* ZeRO-3 ``prefetch`` — **bit-identical** (gathers are pure data movement);
* the interleaved collective matmul — **allclose** only (the chunked
  accumulation reassociates the contraction).

Plus the solver side of the tentpole: the per-op-class overlap factors
must re-price overlapped grid points below their serial pricing, the
SAT-X005 audit stream must calibrate them, and the profile-cache
fingerprint must miss when the factor set (or the lowering version)
moves — a serial profile must never warm-start an overlapped program.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from saturn_tpu.ops.shmap_compat import shard_map
from tests.test_pipeline import (
    _assert_bitwise_equal,
    _assert_close,
    _toy_pipeline,
)

pytestmark = pytest.mark.overlap


# --------------------------------------------------------------- ring hops
class TestRingOverlap:
    def _run(self, overlap, q, k, v, mesh, grads=False):
        from saturn_tpu.ops.ring import ring_attention

        def f(qq, kk, vv):
            return ring_attention(
                qq, kk, vv, axis_name="seq", axis_size=4, overlap=overlap
            )

        sm = shard_map(
            f, mesh=mesh,
            in_specs=(P(None, None, "seq", None),) * 3,
            out_specs=P(None, None, "seq", None),
        )
        if not grads:
            return jax.jit(sm)(q, k, v)

        def loss(qq, kk, vv):
            return jnp.mean(sm(qq, kk, vv) ** 2)

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    @pytest.fixture()
    def qkv_mesh(self, devices8):
        mesh = Mesh(np.array(devices8[:4]).reshape(1, 4), ("data", "seq"))
        B, H, T, D = 2, 2, 32, 8
        keys = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(kk, (B, H, T, D)) for kk in keys)
        return q, k, v, mesh

    def test_forward_bit_identical(self, qkv_mesh):
        q, k, v, mesh = qkv_mesh
        o_serial = self._run(False, q, k, v, mesh)
        o_overlap = self._run(True, q, k, v, mesh)
        _assert_bitwise_equal(o_serial, o_overlap)

    def test_grads_bit_identical(self, qkv_mesh):
        q, k, v, mesh = qkv_mesh
        g_serial = self._run(False, q, k, v, mesh, grads=True)
        g_overlap = self._run(True, q, k, v, mesh, grads=True)
        _assert_bitwise_equal(g_serial, g_overlap)


# ---------------------------------------------------------- pipeline hops
class TestPipelineOverlap:
    @pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
    @pytest.mark.parametrize("remat", [False, True])
    def test_even_spans_bit_identical(self, devices8, schedule, remat):
        from saturn_tpu.ops.pipeline import staged_pipeline_loss_and_grads

        params, tokens, fns, dense_loss = _toy_pipeline(d=2)

        def run(overlap):
            f = jax.jit(lambda p, t: staged_pipeline_loss_and_grads(
                p, t, n_microbatches=4, schedule=schedule, remat=remat,
                overlap=overlap, **fns))
            return f(params, tokens)

        l_serial, g_serial = run(False)
        l_overlap, g_overlap = run(True)
        assert float(jax.device_get(l_serial)) == float(
            jax.device_get(l_overlap))
        _assert_bitwise_equal(g_serial, g_overlap)
        # and both still match the dense model (the knob didn't detach
        # the program from the reference arithmetic, just reorder hops)
        _, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
        _assert_close(g_overlap, g_ref, atol=1e-6)

    def test_uneven_spans_bit_identical(self, devices8):
        from saturn_tpu.ops.pipeline import (
            balance_stages,
            staged_pipeline_loss_and_grads,
        )

        params, tokens, fns, _ = _toy_pipeline(L=6, d=2)
        spans = balance_stages([1.0, 3.0, 1.0, 1.0, 1.0, 1.0], 4)
        assert max(spans) > min(spans)  # genuinely uneven

        def run(overlap):
            f = jax.jit(lambda p, t: staged_pipeline_loss_and_grads(
                p, t, n_microbatches=4, schedule="1f1b",
                stage_spans=spans, overlap=overlap, **fns))
            return f(params, tokens)

        l_serial, g_serial = run(False)
        l_overlap, g_overlap = run(True)
        assert float(jax.device_get(l_serial)) == float(
            jax.device_get(l_overlap))
        _assert_bitwise_equal(g_serial, g_overlap)


# --------------------------------------------------- collective matmul
class TestCollectiveMatmul:
    def test_ring_all_gather_matches_tiled(self, devices8):
        from saturn_tpu.ops.collective_matmul import ring_all_gather

        mesh = Mesh(np.array(devices8[:4]), ("data",))
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 6))

        def f(xs):
            return ring_all_gather(xs, axis_name="data", axis_size=4, axis=0)

        sm = shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P(),
                       check_vma=False)
        np.testing.assert_array_equal(
            np.asarray(jax.jit(sm)(x)), np.asarray(x))

    @pytest.mark.parametrize("overlap", [False, True])
    def test_allgather_matmul_matches_plain(self, devices8, overlap):
        """Both forms vs the unsharded dot_general. The serial form chains
        the hops then contracts once; the overlapped form reassociates —
        allclose is the contract, bitwise is not claimed."""
        from saturn_tpu.ops.collective_matmul import allgather_matmul

        mesh = Mesh(np.array(devices8[:4]), ("data",))
        K, N, B = 16, 10, 5
        x = jax.random.normal(jax.random.PRNGKey(1), (B, K))
        w = jax.random.normal(jax.random.PRNGKey(2), (K, N))

        def f(w_shard):
            return allgather_matmul(
                x, w_shard, axis_name="data", axis_size=4, overlap=overlap
            )

        sm = shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P(),
                       check_vma=False)
        np.testing.assert_allclose(
            np.asarray(jax.jit(sm)(w)), np.asarray(x @ w),
            atol=1e-5, rtol=1e-5,
        )


# -------------------------------------------------------- zero3 prefetch
def _zero3_toy():
    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    L, DM, V, B, T = 4, 16, 31, 8, 12
    params = {
        "emb": jax.random.normal(k1, (V, DM)) * 0.02,
        "blocks": {
            "w": jax.random.normal(k2, (L, DM, DM)) * 0.1,
            "b": jnp.zeros((L, DM)),
        },
        "head": jax.random.normal(k3, (DM, V)) * 0.02,
    }
    tokens = jax.random.randint(k4, (B, T), 0, V)
    fns = dict(
        embed_fn=lambda other, tok: other["emb"][tok],
        block_fn=lambda lp, h: jnp.tanh(h @ lp["w"] + lp["b"]),
        head_fn=lambda other, h: h @ other["head"],
        loss_fn=lambda logits, tok: -jnp.mean(
            jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), tok[..., None], axis=-1
            )
        ),
    )

    def dense_loss(p, tok):
        h = fns["embed_fn"](p, tok)
        h, _ = jax.lax.scan(
            lambda hh, lp: (fns["block_fn"](lp, hh), None), h, p["blocks"])
        return fns["loss_fn"](fns["head_fn"](p, h), tok)

    return params, tokens, fns, dense_loss


class TestZero3Prefetch:
    @pytest.mark.parametrize("remat", [False, True])
    def test_prefetch_bit_identical_and_matches_dense(self, devices8, remat):
        from saturn_tpu.ops.collective_matmul import zero3_loss_and_grads

        params, tokens, fns, dense_loss = _zero3_toy()
        mesh = Mesh(np.array(devices8[:4]), ("data",))

        def run(prefetch):
            f = jax.jit(lambda p, t: zero3_loss_and_grads(
                p, t, mesh=mesh, block_key="blocks", shard_axis="data",
                prefetch=prefetch, remat=remat, min_size=1, **fns))
            return f(params, tokens)

        l_serial, g_serial = run(False)
        l_prefetch, g_prefetch = run(True)
        assert float(jax.device_get(l_serial)) == float(
            jax.device_get(l_prefetch))
        _assert_bitwise_equal(g_serial, g_prefetch)
        l_ref, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
        assert float(l_prefetch) == pytest.approx(float(l_ref), abs=1e-5)
        _assert_close(g_prefetch, g_ref, atol=1e-4)

    def test_tp_form_matches_dense(self, devices8):
        """The (data, model) weight-gathered lowering tp reuses: batch over
        both axes, shards over 'model' — grads must still match dense."""
        from saturn_tpu.ops.collective_matmul import zero3_loss_and_grads

        params, tokens, fns, dense_loss = _zero3_toy()
        mesh = Mesh(np.array(devices8).reshape(2, 4), ("data", "model"))
        f = jax.jit(lambda p, t: zero3_loss_and_grads(
            p, t, mesh=mesh, block_key="blocks", shard_axis="model",
            batch_axes=("data", "model"), prefetch=True, min_size=1, **fns))
        loss, grads = f(params, tokens)
        l_ref, g_ref = jax.value_and_grad(dense_loss)(params, tokens)
        assert float(loss) == pytest.approx(float(l_ref), abs=1e-5)
        _assert_close(grads, g_ref, atol=1e-4)


# ------------------------------------------------- solver repricing
class TestOverlapPricing:
    def _toy_ledger(self):
        from saturn_tpu.analysis.shardflow.interp import (
            CollectiveRecord, CommLedger,
        )

        led = CommLedger(flops=4e12)
        led.add(CollectiveRecord(
            op="all_gather", axes=("data",), bytes=10**8, wire_bytes=2e8,
            count=4, primitive="all_gather", provenance="t"))
        led.add(CollectiveRecord(
            op="all_reduce", axes=("data",), bytes=10**8, wire_bytes=1e8,
            count=1, primitive="psum", provenance="t"))
        return led

    def test_overlapped_estimate_below_serial(self):
        from saturn_tpu.analysis.shardflow import prior

        led = self._toy_ledger()
        serial = prior.estimate_step_seconds(led, 4)
        overlapped = prior.estimate_step_seconds(led, 4, overlap=True)
        assert overlapped < serial
        # all_reduce carries factor 0: only the gather discount applies
        by_op = prior.comm_seconds_by_op(led)
        f = prior.overlap_factors()
        expected = serial - by_op["all_gather"] * f["all_gather"]
        assert overlapped == pytest.approx(expected, rel=1e-9)

    def test_prior_reprices_overlapped_technique(self, tiny_task, devices8):
        """The admission-path pricing: trace the fsdp overlap grid point
        through shardflow and the overlap factors must price it strictly
        below the same ledger priced serial."""
        from saturn_tpu.analysis.shardflow.interp import interpret
        from saturn_tpu.analysis.shardflow import prior
        from saturn_tpu.parallel.fsdp import FSDP

        tech = FSDP()
        config = next(c for c in tech.candidate_configs(tiny_task, 4)
                      if c.get("overlap"))
        traced = tech.trace_step(tiny_task, devices8[:4], config)
        ledger = interpret(traced)
        serial = prior.estimate_step_seconds(ledger, 4, overlap=False)
        overlapped = prior.estimate_step_seconds(ledger, 4, overlap=True)
        assert overlapped < serial

    def test_calibration_moves_factors_and_repricing(self):
        """A measured step faster than the serial static estimate raises
        the gather factor, and the next estimate drops accordingly."""
        from saturn_tpu.analysis.shardflow import prior

        led = self._toy_ledger()
        by_op = prior.comm_seconds_by_op(led)
        serial = prior.estimate_step_seconds(led, 4)
        compute_s = serial - sum(by_op.values())

        class _Strat:
            pass

        class _Task:
            pass

        strat = _Strat()
        strat._static_overlap = True
        strat.static_prior = False  # measurement landed
        strat._static_comm_by_op = by_op
        strat._static_compute_s = compute_s
        # measured: the gather fully hidden, the all_reduce still paid
        strat.per_batch_time = compute_s + by_op["all_reduce"]
        task = _Task()
        task.strategies = {4: strat}

        prior.reset_overlap_calibration()
        try:
            before_f = prior.overlap_factors()["all_gather"]
            before_t = prior.estimate_step_seconds(led, 4, overlap=True)
            after = prior.calibrate_overlap_factors([task])
            assert after["all_gather"] > before_f
            after_t = prior.estimate_step_seconds(led, 4, overlap=True)
            assert after_t < before_t
        finally:
            prior.reset_overlap_calibration()

    def test_synthesize_stashes_calibration_inputs(self, tiny_task,
                                                   devices8):
        """Cold-start strategies carry the static decomposition the
        calibrator needs once a measurement supersedes them."""
        from saturn_tpu.analysis.shardflow import prior
        from saturn_tpu.core.mesh import SliceTopology

        topo = SliceTopology(devices8)
        added = prior.synthesize_strategies(
            tiny_task, topo, technique_names=["fsdp"])
        assert added
        strat = tiny_task.strategies[added[0]]
        assert hasattr(strat, "_static_overlap")
        assert isinstance(strat._static_comm_by_op, dict)
        assert strat._static_compute_s >= 0.0


# ------------------------------------------------- fingerprint identity
class TestOverlapFingerprint:
    def test_factor_change_misses(self, monkeypatch):
        """A profile priced under one factor set must not warm-start a run
        under another: env-pinning one factor changes every fingerprint."""
        from saturn_tpu.utils import profile_cache as pc

        base = pc.fingerprint("task", "fsdp", 4, "topo")
        monkeypatch.setenv("SATURN_TPU_PRIOR_OVERLAP_ALL_GATHER", "0.95")
        pinned = pc.fingerprint("task", "fsdp", 4, "topo")
        assert pinned != base
        monkeypatch.delenv("SATURN_TPU_PRIOR_OVERLAP_ALL_GATHER")
        assert pc.fingerprint("task", "fsdp", 4, "topo") == base

    def test_lowering_version_in_signature(self):
        from saturn_tpu.ops.collective_matmul import OVERLAP_SET_VERSION
        from saturn_tpu.utils import profile_cache as pc

        sig = pc.overlap_signature()
        assert f"comm-overlap-v{OVERLAP_SET_VERSION}" in sig
        # and the active factor set rides along
        assert "all_gather=" in sig

    def test_calibration_misses(self):
        """Recalibrated factors invalidate cache entries priced under the
        old set — the stale-serial-profile guarantee of the tentpole."""
        from saturn_tpu.analysis.shardflow import prior
        from saturn_tpu.utils import profile_cache as pc

        prior.reset_overlap_calibration()
        try:
            base = pc.fingerprint("task", "fsdp", 4, "topo")
            prior._calibrated_factors["all_gather"] = 0.91
            assert pc.fingerprint("task", "fsdp", 4, "topo") != base
        finally:
            prior.reset_overlap_calibration()
        assert pc.fingerprint("task", "fsdp", 4, "topo") == base
