"""Persistent profile cache + cost-model pruning (trial_runner/evaluator.py).

Hardware-free: fake techniques count ``search`` invocations so the tests can
assert the sweep's *compile economy* — zero trials on an identical re-run,
anchor-only trials under pruning, no trials below a memory-infeasible size —
without ever jitting a program.
"""

import json
import os

import pytest

from saturn_tpu import library
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.trial_runner import evaluator
from saturn_tpu.utils import profile_cache as pcache


class FakeDev:
    platform = "cpu"
    device_kind = "fake-cpu"
    process_index = 0


def topo(n=8):
    return SliceTopology([FakeDev() for _ in range(n)])


class FakeSpec:
    def __init__(self, config):
        self.config = config


class FakeDataset:
    batch_size = 8

    def __len__(self):
        return 8

    def example_batch(self):
        import numpy as np

        return np.zeros((8, 64), dtype=np.int32)

    def batch(self, i):
        return self.example_batch()


class FakeHParams:
    optimizer = "adamw"
    kwargs: dict = {}


class FakeTask:
    """Evaluator-facing duck type (name, chip_range, strategies, factories)."""

    def __init__(self, name, model_cfg="cfg-v1", optimizer="adamw"):
        self.name = name
        self.chip_range = None
        self.total_batches = 100
        self.strategies = {}
        self.hints = {}
        self.hparams = FakeHParams()
        self.hparams.optimizer = optimizer
        self._model_cfg = model_cfg

    def get_model(self, **kw):
        return FakeSpec(self._model_cfg)

    def get_dataset(self):
        return FakeDataset()

    def feasible_strategies(self):
        return {g: s for g, s in self.strategies.items() if s.feasible}


class CountingTech(BaseTechnique):
    """Feasible everywhere; records every (task, size) search invocation."""

    name = "counting"
    calls: list = []

    def search(self, task, devices, tid):
        type(self).calls.append((task.name, len(devices)))
        g = len(devices)
        return {"knob": g}, 0.08 / g + 0.02  # Amdahl-ish: a=0.02, b=0.08

    def execute(self, task, devices, tid, override_batch_count=None):
        pass


class MemoryWallTech(BaseTechnique):
    """Memory-infeasible below 8 chips, with an honest search report."""

    name = "memwall"
    memory_monotone = True
    calls: list = []

    def __init__(self):
        self._reports = {}

    def search(self, task, devices, tid):
        g = len(devices)
        type(self).calls.append((task.name, g))
        if g < 8:
            self._reports[(task.name, g)] = {"memory_infeasible": True}
            return None, None
        return {}, 0.01

    def search_report(self, task_name, size):
        return self._reports.pop((task_name, size), None)

    def execute(self, task, devices, tid, override_batch_count=None):
        pass


@pytest.fixture(autouse=True)
def _registry():
    library.register("counting", CountingTech)
    library.register("memwall", MemoryWallTech)
    CountingTech.calls = []
    MemoryWallTech.calls = []
    yield
    library.deregister("counting")
    library.deregister("memwall")


def run_search(tasks, names, cache_dir, prune=False, metrics_path=None, n=8):
    evaluator.search(
        tasks,
        technique_names=names,
        topology=topo(n),
        profile_cache=cache_dir if cache_dir is not None else False,
        prune=prune,
        metrics_path=metrics_path,
    )


def read_events(path, kind):
    with open(path) as f:
        return [json.loads(line) for line in f if json.loads(line)["kind"] == kind]


class TestPersistentCache:
    def test_rerun_is_trial_free(self, tmp_path):
        """Acceptance: a second search() over an unchanged task list performs
        ZERO technique.search executions — every strategy comes from the
        persistent profile cache."""
        cache_dir = str(tmp_path / "cache")
        mpath = str(tmp_path / "m1.jsonl")
        tasks = [FakeTask("a"), FakeTask("b")]
        run_search(tasks, ["counting"], cache_dir, metrics_path=mpath)
        assert len(CountingTech.calls) == 2 * 4  # 2 tasks x sizes {1,2,4,8}
        first = {
            (t.name, g): s.per_batch_time
            for t in tasks for g, s in t.strategies.items() if s.feasible
        }
        assert len(first) == 8

        CountingTech.calls = []
        mpath2 = str(tmp_path / "m2.jsonl")
        rerun = [FakeTask("a"), FakeTask("b")]  # same content, fresh objects
        run_search(rerun, ["counting"], cache_dir, metrics_path=mpath2)
        assert CountingTech.calls == []
        for t in rerun:
            for g, s in t.strategies.items():
                assert s.feasible, (t.name, g)
                assert s.per_batch_time == pytest.approx(first[(t.name, g)])
                assert not s.interpolated
                assert s.cache_key
        hits = read_events(mpath2, "profile_cache")
        assert sum(1 for e in hits if e.get("hit")) == 8
        misses = [e for e in read_events(mpath, "profile_cache") if not e.get("hit")]
        assert len(misses) == 8  # first run consulted and missed every point

    def test_model_change_misses(self, tmp_path):
        cache_dir = str(tmp_path / "cache")
        run_search([FakeTask("a", model_cfg="cfg-v1")], ["counting"], cache_dir)
        CountingTech.calls = []
        run_search([FakeTask("a", model_cfg="cfg-v2")], ["counting"], cache_dir)
        assert len(CountingTech.calls) == 4  # every size re-profiled

    def test_optimizer_change_misses(self, tmp_path):
        cache_dir = str(tmp_path / "cache")
        run_search([FakeTask("a")], ["counting"], cache_dir)
        CountingTech.calls = []
        run_search([FakeTask("a", optimizer="sgd")], ["counting"], cache_dir)
        assert len(CountingTech.calls) == 4

    def test_topology_change_misses(self, tmp_path):
        cache_dir = str(tmp_path / "cache")
        run_search([FakeTask("a")], ["counting"], cache_dir, n=8)
        CountingTech.calls = []
        run_search([FakeTask("a")], ["counting"], cache_dir, n=4)
        # sizes {1,2,4} on the 4-dev topology: all missed despite overlapping
        # sizes with the 8-dev run (topology signature differs)
        assert len(CountingTech.calls) == 3

    def test_schedule_set_change_misses(self, tmp_path, monkeypatch):
        """Round 20: the pipeline schedule set is part of the fingerprint —
        a profile recorded under a gpipe-only sweep must miss once 1F1B
        joins the grid (execution would route cached configs differently)."""
        cache_dir = str(tmp_path / "cache")
        monkeypatch.setattr(pcache, "schedule_signature", lambda: "gpipe-only")
        run_search([FakeTask("a")], ["counting"], cache_dir)
        CountingTech.calls = []
        monkeypatch.setattr(
            pcache, "schedule_signature", lambda: "gpipe+1f1b:v1")
        run_search([FakeTask("a")], ["counting"], cache_dir)
        assert len(CountingTech.calls) == 4  # every size re-trialed

    def test_schedule_signature_resolves_from_ops(self):
        from saturn_tpu.ops.pipeline import SCHEDULE_SET_VERSION

        assert pcache.schedule_signature() == SCHEDULE_SET_VERSION

    def test_corrupt_and_stale_entries_are_misses(self, tmp_path):
        cache_dir = str(tmp_path / "cache")
        run_search([FakeTask("a")], ["counting"], cache_dir)
        files = [f for f in os.listdir(cache_dir) if f.endswith(".json")]
        assert len(files) == 4
        # corrupt half the files, swap the rest's key field (stale/foreign)
        for i, fn in enumerate(sorted(files)):
            p = os.path.join(cache_dir, fn)
            if i % 2 == 0:
                with open(p, "w") as f:
                    f.write("{not json at all")
            else:
                with open(p) as f:
                    e = json.load(f)
                e["key"] = "0" * 64
                with open(p, "w") as f:
                    json.dump(e, f)
        CountingTech.calls = []
        run_search([FakeTask("a")], ["counting"], cache_dir)  # must not raise
        assert len(CountingTech.calls) == 4  # everything re-profiled

    def test_infeasible_outcomes_are_cached(self, tmp_path):
        cache_dir = str(tmp_path / "cache")
        run_search([FakeTask("a")], ["memwall"], cache_dir, prune=False)
        # descending sizes: 8 feasible, 4 memory-infeasible, 1/2 pruned
        assert MemoryWallTech.calls == [("a", 8), ("a", 4)]
        MemoryWallTech.calls = []
        t2 = FakeTask("a")
        run_search([t2], ["memwall"], cache_dir, prune=False)
        # hit on 8 (feasible) and 4 (memory-infeasible) -> 1/2 pruned again
        assert MemoryWallTech.calls == []
        assert t2.strategies[8].feasible
        for g in (1, 2, 4):
            assert not t2.strategies[g].feasible

    def test_note_realized_upgrades_entry(self, tmp_path):
        cache = pcache.ProfileCache(str(tmp_path / "c"))
        key = pcache.fingerprint("sig", "dp", 4, "topo")
        cache.put(key, technique="dp", size=4, feasible=True,
                  params={"remat": False}, per_batch_time=0.5)
        assert cache.note_realized(key, 0.8, None, technique="dp", size=4)
        e = cache.get(key)
        assert e["per_batch_time"] == pytest.approx(0.8)
        assert e["source"] == "realized"
        assert e["params"] == {"remat": False}  # kept from the trial entry


class TestPruning:
    def test_anchors_only_full_table(self, tmp_path):
        """Acceptance: with pruning on a >= 4-size grid, at most the anchor
        sizes are compiled per (task, technique), yet every valid size has a
        strategy entry (interpolated ones flagged) and the solver still
        plans on the result."""
        t = FakeTask("a")
        run_search([t], ["counting"], None, prune=True)
        sizes_run = sorted(g for _, g in CountingTech.calls)
        assert sizes_run == [1, 4, 8]  # min, midpoint, max of {1,2,4,8}
        assert set(t.strategies) == {1, 2, 4, 8}
        assert not t.strategies[1].interpolated
        assert not t.strategies[4].interpolated
        assert not t.strategies[8].interpolated
        s2 = t.strategies[2]
        assert s2.feasible and s2.interpolated
        # the Amdahl fit over exact a + b/g points reproduces the law
        assert s2.per_batch_time == pytest.approx(0.08 / 2 + 0.02, rel=1e-6)
        assert s2.params == {"knob": 1} or s2.params == {"knob": 4}

        from saturn_tpu.solver.milp import solve

        plan = solve([t], topo(8), time_limit=10.0)
        assert t.name in plan.assignments

    def test_small_grids_not_pruned(self, tmp_path):
        t = FakeTask("a")
        t.chip_range = [1, 2, 4]
        run_search([t], ["counting"], None, prune=True)
        assert sorted(g for _, g in CountingTech.calls) == [1, 2, 4]
        assert not any(s.interpolated for s in t.strategies.values())

    def test_memory_infeasibility_propagates_down(self, tmp_path):
        """A memory rejection at size g skips every smaller size (per-chip
        memory there is >= the rejected size's) instead of compiling it."""
        t = FakeTask("a")
        mpath = str(tmp_path / "m.jsonl")
        run_search([t], ["memwall"], None, prune=True, metrics_path=mpath)
        # anchors {1, 4, 8} descending: 8 feasible, 4 memory-infeasible,
        # 1 pruned without a search; non-anchor 2 pruned in the fill pass
        assert MemoryWallTech.calls == [("a", 8), ("a", 4)]
        assert t.strategies[8].feasible
        for g in (1, 2, 4):
            assert not t.strategies[g].feasible
        pruned = read_events(mpath, "trial_pruned")
        assert {e["size"] for e in pruned} == {1, 2}
        assert all(e["reason"] == "memory_monotone" for e in pruned)

    def test_interpolation_skipped_without_signal(self, tmp_path):
        """One measured point is no scaling model: unmeasured sizes stay
        infeasible dummies rather than fabricated estimates."""

        class OnlyMax(CountingTech):
            name = "onlymax"
            calls = []

            def search(self, task, devices, tid):
                type(self).calls.append((task.name, len(devices)))
                if len(devices) < 8:
                    return None, None  # infeasible, but NOT memory-reported
                return {}, 0.01

        library.register("onlymax", OnlyMax)
        try:
            t = FakeTask("a")
            run_search([t], ["onlymax"], None, prune=True)
            # no memory report -> no propagation: all anchors searched
            assert sorted(g for _, g in OnlyMax.calls) == [1, 4, 8]
            assert t.strategies[8].feasible
            assert not t.strategies[2].feasible  # dummy, not interpolated
        finally:
            library.deregister("onlymax")


class TestRealizedFeedbackUpgrade:
    def test_feedback_clears_interpolated_flag(self, tiny_task):
        s = Strategy(object(), 2, {"remat": False}, 5.0, per_batch_time=0.5,
                     interpolated=True, cache_key="k")
        tiny_task.strategies[2] = s
        tiny_task.select_strategy(2)
        tiny_task.note_realized_per_batch(0.3)
        upd = tiny_task.apply_realized_feedback()
        assert upd is not None
        assert s.interpolated is False
        assert tiny_task.last_feedback_strategy is s


class TestEtaTracker:
    def test_running_average(self):
        eta = evaluator._EtaTracker(planned=4, hits=2, deferred=1)
        assert "4 trials to run" in eta.start_message()
        assert "2 profile-cache hits" in eta.start_message()
        msg = eta.trial_done(2.0)
        assert "1/4" in msg and "avg 2.0s/trial" in msg and "ETA 6s" in msg
        eta.trial_pruned()
        msg = eta.trial_done(4.0)
        assert "2/3" in msg and "avg 3.0s/trial" in msg and "ETA 3s" in msg
