"""Mixture-of-experts op, model, and expert-parallel executor tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from saturn_tpu.ops.moe import expert_capacity, switch_moe


# Multi-device-compile-heavy on the 1-core CI host (VERDICT r3 item 7):
# these mesh suites are the slow tier; run with -m slow (or no -m filter).
pytestmark = pytest.mark.slow


def dense_reference(x, router_w, we_in, be_in, we_out, be_out):
    """Per-token loop reference: each token goes to its argmax expert (no
    capacity drops), output scaled by the gate probability."""
    B, T, D = x.shape
    xf = x.reshape(-1, D)
    logits = xf @ router_w
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = np.zeros_like(np.asarray(xf), dtype=np.float32)
    for s in range(xf.shape[0]):
        e = int(np.argmax(probs[s]))
        h = np.asarray(xf[s]) @ np.asarray(we_in[e]) + np.asarray(be_in[e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(h), approximate=True))
        y = h @ np.asarray(we_out[e]) + np.asarray(be_out[e])
        out[s] = float(probs[s, e]) * y
    return out.reshape(B, T, D)


class TestSwitchMoe:
    def _mk(self, B=2, T=8, D=16, E=4, F=32, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda *s: jnp.asarray(rng.standard_normal(s) * 0.1, jnp.float32)
        return (
            mk(B, T, D), mk(D, E), mk(E, D, F), mk(E, F), mk(E, F, D), mk(E, D),
        )

    def test_capacity(self):
        assert expert_capacity(64, 4, 1.0) == 16
        assert expert_capacity(64, 4, 1.25) == 20
        assert expert_capacity(3, 8, 1.0) == 1

    def test_matches_dense_routing(self):
        x, rw, wi, bi, wo, bo = self._mk()
        # capacity_factor big enough that nothing is dropped
        y, aux = switch_moe(x, rw, wi, bi, wo, bo, capacity_factor=4.0)
        ref = dense_reference(x, rw, wi, bi, wo, bo)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)
        assert np.isfinite(float(aux)) and float(aux) > 0

    def test_capacity_drops_tokens(self):
        x, rw, wi, bi, wo, bo = self._mk()
        # tiny capacity: most tokens dropped -> output much smaller in norm
        y_full, _ = switch_moe(x, rw, wi, bi, wo, bo, capacity_factor=4.0)
        y_tiny, _ = switch_moe(x, rw, wi, bi, wo, bo, capacity_factor=0.1)
        assert np.linalg.norm(np.asarray(y_tiny)) < np.linalg.norm(np.asarray(y_full))

    def test_aux_loss_balanced_is_one(self):
        """Perfectly uniform routing gives aux = E * E * (1/E * 1/E) = 1."""
        B, T, D, E = 1, 16, 8, 4
        x = jnp.zeros((B, T, D))
        rw = jnp.zeros((D, E))  # uniform probs; argmax ties -> expert 0
        wi = jnp.zeros((E, D, 8)); bi = jnp.zeros((E, 8))
        wo = jnp.zeros((E, 8, D)); bo = jnp.zeros((E, D))
        _, aux = switch_moe(x, rw, wi, bi, wo, bo)
        # all tokens on expert 0: aux = E * (1 * 1/E) = 1
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


class TestMoeModel:
    @pytest.fixture(scope="class")
    def moe_spec(self):
        from saturn_tpu.models.gpt2 import build_gpt2

        return build_gpt2("moe-test-tiny")

    def test_forward_and_aux(self, moe_spec):
        cfg = moe_spec.config
        params = moe_spec.init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, cfg.seq_len), 0, 255)
        logits = moe_spec.apply_fn(params, tokens)  # plain path: sow is a no-op
        assert logits.shape == (2, cfg.seq_len, cfg.vocab_size)
        logits2, aux = moe_spec.apply_with_aux_fn(params, tokens)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2),
                                   rtol=1e-5, atol=1e-5)
        assert float(aux) > 0  # E * sum(f*P) >= 1 when weight > 0

    def test_expert_tables_scanned(self, moe_spec):
        cfg = moe_spec.config
        shapes = moe_spec.abstract_init()
        we_in = shapes["blocks"]["we_in"]
        assert we_in.shape == (cfg.n_layers, cfg.n_experts, cfg.d_model, cfg.ff_dim)

    def test_trains(self, moe_spec):
        from tests.test_models import check_trains

        check_trains(moe_spec)


@pytest.fixture()
def moe_task(tmp_path):
    from saturn_tpu import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    return Task(
        get_model=lambda **kw: build_gpt2("moe-test-tiny", **kw),
        get_dataloader=lambda: make_lm_dataset(
            context_length=64, batch_size=8, vocab_size=256, n_tokens=64 * 8 * 8
        ),
        loss_fn=pretraining_loss,
        hparams=HParams(lr=1e-3, batch_count=16),
        save_dir=str(tmp_path / "ckpts"),
    )


class TestExpertParallel:
    def test_search_execute_ckpt(self, moe_task, devices8):
        from saturn_tpu.parallel.ep import ExpertParallel
        from tests.test_executors import run_search_and_execute

        run_search_and_execute(ExpertParallel(), moe_task, devices8[:4])

    def test_expert_axis_sharded(self, moe_task, devices8):
        from saturn_tpu.parallel.ep import ExpertParallel

        tech = ExpertParallel()
        bundle = tech.build(moe_task, devices8[:4], {"ep": 2, "remat": False})
        sh = bundle.state_shardings["params"]["blocks"]["we_in"]
        # positional: dim 0 is the layer scan, dim 1 is the expert axis
        assert tuple(sh.spec)[1] == "expert", f"expert dim not sharded: {sh.spec}"
        # router replicated
        r = bundle.state_shardings["params"]["blocks"]["router"]
        assert r.is_fully_replicated

    def test_expert_rule_layer_collision(self):
        """n_layers == n_experts must still shard dim 1, not the scan dim."""
        from saturn_tpu.parallel.ep import expert_rules

        rules = expert_rules("expert", 4)
        spec = rules("params/blocks/we_in", (4, 4, 16, 32), {"expert": 2})
        assert tuple(spec) == (None, "expert", None, None)
        # unscanned table: expert dim is dim 0
        spec0 = rules("params/we_in", (4, 16, 32), {"expert": 2})
        assert tuple(spec0) == ("expert", None, None)

    def test_objective_consistent_across_techniques(self, moe_task, devices8):
        """Every standard technique must train the same objective (user loss
        + aux) — interval-boundary technique switches must not change it."""
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.dp import DataParallel
        from saturn_tpu.parallel.ep import ExpertParallel

        dp, ep = DataParallel(), ExpertParallel()
        b_dp = dp.build(moe_task, devices8[:2], {"remat": False})
        b_ep = ep.build(moe_task, devices8[:4], {"ep": 2, "remat": False})
        s_dp, s_ep = b_dp.init(), b_ep.init()
        batch = moe_task.batch_at(0)
        _, l_dp = b_dp.step(s_dp, jax.device_put(batch, b_dp.batch_sharding))
        _, l_ep = b_ep.step(s_ep, jax.device_put(batch, b_ep.batch_sharding))
        np.testing.assert_allclose(float(l_dp), float(l_ep), rtol=2e-2)
        # and both equal user loss + aux on the same init params
        spec = moe_task.get_model()
        params = spec.init_fn(jax.random.PRNGKey(0))
        logits, aux = spec.apply_with_aux_fn(params, jnp.asarray(batch))
        want = float(pretraining_loss(logits, jnp.asarray(batch))) + float(aux)
        np.testing.assert_allclose(float(l_dp), want, rtol=2e-2)

    def test_aux_dropping_techniques_infeasible(self, moe_task, devices8):
        """pp/ring/offload-streaming replace the forward pass: they must
        declare MoE (aux-loss) models infeasible rather than silently drop
        the balancing term."""
        from saturn_tpu.parallel.pp import Pipeline
        from saturn_tpu.parallel.ring import RingSequenceParallel

        assert Pipeline().candidate_configs(moe_task, 8) == []
        assert RingSequenceParallel().candidate_configs(moe_task, 8) == []
        from saturn_tpu.parallel.offload import HostOffload

        assert all(
            not c.get("stream") for c in HostOffload().candidate_configs(moe_task, 8)
        )

    def test_bulk_offload_keeps_aux(self, moe_task, devices8):
        """Bulk (non-streaming) offload wraps the forward pass but must still
        train user loss + aux, matching every other standard technique."""
        from saturn_tpu.models.loss import pretraining_loss
        from saturn_tpu.parallel.offload import HostOffload

        off = HostOffload()
        b = off.build(moe_task, devices8[:2], {"stream": False, "remat": False})
        state = b.init()
        batch = moe_task.batch_at(0)
        _, loss = b.step(state, jax.device_put(batch, b.batch_sharding))
        spec = moe_task.get_model()
        params = spec.init_fn(jax.random.PRNGKey(0))
        logits, aux = spec.apply_with_aux_fn(params, jnp.asarray(batch))
        want = float(pretraining_loss(logits, jnp.asarray(batch))) + float(aux)
        np.testing.assert_allclose(float(loss), want, rtol=2e-2)

    def test_dense_model_infeasible(self, tiny_task, devices8):
        from saturn_tpu.parallel.ep import ExpertParallel

        params, t = ExpertParallel().search(tiny_task, devices8[:4], tid=0)
        assert params is None and t is None


class TestAuxGuard:
    """ADVICE r1: custom-schedule step fns must raise on aux-loss models,
    not silently train without the load-balance term."""

    def test_pp_and_streaming_offload_raise(self, moe_task, devices8):
        from saturn_tpu.parallel.offload import HostOffload
        from saturn_tpu.parallel.pp import Pipeline

        with pytest.raises(ValueError, match="auxiliary loss"):
            Pipeline().build(
                moe_task, devices8[:2],
                {"stages": 2, "microbatches": 2, "remat": False},
            )
        with pytest.raises(ValueError, match="auxiliary loss"):
            HostOffload().build(
                moe_task, devices8[:2], {"stream": True, "remat": True}
            )

    def test_ring_raises(self, moe_task, devices8):
        from saturn_tpu.parallel.ring import RingSequenceParallel

        with pytest.raises(ValueError, match="auxiliary loss"):
            RingSequenceParallel().build(
                moe_task, devices8[:2], {"sp": 2, "remat": False}
            )
